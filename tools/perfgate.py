"""Perf regression gate: compare the newest bench artifact to baseline.

Every round leaves a `BENCH_r*.json` artifact (and servebench can write
its own with `--json`), but nothing READ them — a PR could halve
windows/s and CI would stay green. `perfgate` closes the loop with one
line of verdict and an exit status:

    python tools/perfgate.py                    # newest BENCH_r*.json
    python tools/perfgate.py --artifact out.json --tolerance-pct 10
    python tools/perfgate.py --against auto     # vs the previous round

Metric extraction understands both artifact shapes:

  - bench.py lines (possibly wrapped by the driver as {"parsed": ...}):
    `value` in windows/sec, HIGHER is better. Artifacts whose metric
    ends in `_failed`, whose value is 0, or whose rc is nonzero are
    SKIPPED (a timed-out round is not a baseline and not a candidate).
  - servebench `--json` artifacts (`"mode": "serve"`): warm sequential
    p50 seconds, LOWER is better — gated against the baseline like the
    bench quotients — PLUS the artifact's SLO miss rate (`slo.
    miss_rate`), gated ABSOLUTELY against `--slo-miss-rate` (default
    0.0: any deadline miss fails the gate) when the artifact carries an
    slo view or the limit was requested explicitly, PLUS the
    continuous-batching tail metrics `warm.p99_s` (wave p99) and
    `warm.ttfb_p50_s` (time-to-first-byte p50): each gates ABSOLUTELY
    against `--p99-max` / `--ttfb-p50-max` when requested, and
    RELATIVELY (tolerance-pct) against the `--against` reference
    whenever both artifacts carry the key.

  - servebench `--audit-rate` artifacts carry an `audit` block (the
    identity-audit sentinel's measured cost): `audit.overhead_pct` —
    the A/B wall delta of the audited vs muted sequential pass — gates
    ABSOLUTELY at the established observability budget (default 2.0
    whenever the block is present; `--audit-overhead-max` makes it
    mandatory, rc 2 naming the dotted key when absent), and
    `audit.mismatches` must be ZERO whenever the block is present (a
    sentinel mismatch on a clean bench workload is silent corruption,
    not a perf number).

  - servebench `--fleet` artifacts additionally carry a `fleet` block:
    `fleet.scrape_overhead_pct` — replica time spent answering the
    aggregator's scrape+healthz polls as a percentage of the wave —
    gates ABSOLUTELY at the established observability budget (default
    2.0 whenever the block is present; `--scrape-overhead-max` makes
    it mandatory, rc 2 naming the dotted key when absent).

  - servebench `--router` artifacts (`"mode": "router"`) carry a
    `router` block (the shard-aware fan-out's scaling curve):
    `router.identical` — byte-identity of the routed FASTA vs a direct
    single-replica submit — gates whenever the block is present, as
    does `router.requeues` == 0 (a requeue on a healthy bench fleet is
    a real replica loss, not noise); `router.scaling_x` (jobs/s at N
    replicas over jobs/s at 1) gates ABSOLUTELY against
    `--router-scaling-min` (mandatory once requested, rc 2 naming the
    dotted key when absent); `router.range_scaling_x` (single-job wall
    at 1 replica over the wall at the highest swept count — the
    window-range-sharding speedup a `--contigs 1` workload measures)
    gates ABSOLUTELY against `--range-scaling-min` (mandatory once
    requested, rc 2 naming the dotted key when absent). The headline
    `router.jobs_per_s` gates RELATIVELY only against an explicit
    `--against` router artifact — there is no implicit baseline for a
    replica-count sweep. Router artifacts may also carry a `trace`
    block (the traced-vs-untraced sequential A/B at the top count):
    `trace.overhead_pct` gates ABSOLUTELY at the established
    observability budget (default 2.0 whenever the block is present;
    `--trace-overhead-max` makes it mandatory, rc 2 naming the dotted
    key when absent).

  - servebench `--ramp` artifacts (`"mode": "ramp"`) carry an
    `autoscale` block (the elastic-fleet loop under a 1x->10x Poisson
    ramp): `autoscale.jobs_lost` must be ZERO whenever the block is
    present (a job lost across scale-up/scale-down is the race the
    unroute-then-drain handshake exists to prevent, never noise), and
    `autoscale.gold_p99_flat` — gold p99 over the ramp divided by the
    idle 1-replica p99 — gates ABSOLUTELY whenever the block is
    present (default 2.0; `--ramp-p99-flat-max` makes it mandatory,
    rc 2 naming the dotted key when absent). Like router sweeps, ramp
    artifacts have no implicit baseline (the idle arm inside the
    artifact is the comparison).

  - servebench `--rounds` artifacts (`"mode": "rounds"`) carry
    `rounds` / `cache` blocks (serve-native iterative polishing with
    the content-addressed window cache): `cache.identical` — the
    cached rounds FASTA byte-equal to the cache-off bytes — gates
    whenever the block is present, as does a NONZERO `cache.hit_rate`
    (a cache that never engaged measured nothing) and, when the
    artifact carries an audit block, `audit.mismatches` == 0;
    `rounds.round2_speedup_x` (mean no-cache round-2+ wall over mean
    cached round-2+ wall) gates ABSOLUTELY against
    `--round2-speedup-min` (mandatory once requested, rc 2 naming the
    dotted key when absent). Like router sweeps, rounds artifacts have
    no implicit baseline.

  - servebench `--flood` artifacts (`"mode": "flood"`) carry a `qos`
    block (preemptive-QoS isolation under a free-tenant flood):
    `qos.gold_p99_flat` — gold-tenant p99 under flood-with-preemption
    over gold p99 on an idle fabric — gates ABSOLUTELY whenever the
    block is present (default 2.0; `--gold-p99-flat-max` makes it
    mandatory, rc 2 naming the dotted key when absent), and
    `qos.doomed_abort_saved_s` (EMA-predicted device seconds the
    speculative deadline-aborts saved) gates against
    `--doomed-abort-min`, mandatory once requested — an artifact
    without the key exits 2 naming it. Like router sweeps, flood
    artifacts have no implicit baseline (the idle arm inside the
    artifact is the comparison).

  - synthbench `--json` artifacts (`"mode": "synth"`):
    `synth.windows_per_s`, HIGHER is better — gated ABSOLUTELY against
    `--windows-per-s-min` (the kernel-plane regression floor) and
    RELATIVELY against a prior synth artifact via `--against`. Synth
    artifacts have no implicit baseline (the published BASELINE numbers
    measure the reference sample, a different workload), so with only
    the floor requested the relative gate is skipped.

  - synthbench artifacts with device consensus armed also carry a
    `fused` block (the dispatch-loop view): `fused.host_frac` — the
    measured host-overhead fraction of the polish wall — gates
    ABSOLUTELY (default 0.75 whenever the block is present;
    `--host-frac-max` makes it mandatory, rc 2 naming the dotted key
    when absent). The windows/s floor stays mandatory alongside it.

  - synthbench `--scale-curve` artifacts additionally carry a `scale`
    block: gated on byte-identity across mesh sizes, per-shard
    useful-cell balance (`--scale-balance-max`, default 1.5 when the
    block is present) and each multi-device point's padded-cell
    fraction sitting STRICTLY below its full-mesh-rounding baseline.

Artifacts that record a `mesh` block ({n_devices, worker_lanes, ...})
are only compared against references measured on the SAME mesh — a
cross-mesh `--against` exits 2 naming the mismatched key
(`mesh.n_devices` / `mesh.worker_lanes`).

A missing gated metric is a BROKEN GATE, not a traceback: the error
names the dotted key (`warm.seq_p50_s`, `slo.miss_rate`,
`warm.p99_s`, `warm.ttfb_p50_s`, `synth.windows_per_s`,
`scale.curve`) and exits 2, so CI can tell "the artifact changed
shape" from "perf regressed".

Baseline resolution, in order:

  1. `--ref-value X` — an explicit number (CI pinning a known-good run).
  2. `--against PATH` — another artifact; `--against auto` = the newest
     usable artifact BEFORE the candidate (round-over-round gating;
     noisier, so pick your tolerance accordingly).
  3. BASELINE.json `published.windows_per_sec` when someone has
     published a measured baseline there.
  4. The artifact's own `vs_baseline` ratio, which bench.py defines
     against the reference CPU implementation's throughput — the
    `value / vs_baseline` quotient IS the baseline the repo has been
     comparing against since round 1 (50 windows/s on the sample).

The default tolerance is 10%: a candidate more than 10% WORSE than the
baseline (slower windows/s, or higher serve p50) exits 1. bench.py runs
the gate automatically after emitting its metric line when
RACON_TPU_PERFGATE=1 (stderr verdict only — the JSON-line contract is
untouched).
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class GateError(Exception):
    """Artifact unusable / baseline unresolvable (exit 2, not 1: a
    broken gate must be distinguishable from a real regression)."""


def load_artifact(path: str) -> dict:
    try:
        with open(path) as fh:
            doc = json.load(fh)
    except (OSError, ValueError) as exc:
        raise GateError(f"cannot read artifact {path}: {exc}") from None
    if not isinstance(doc, dict):
        raise GateError(f"artifact {path} is not a JSON object")
    return doc


def _lookup(inner: dict, dotted: str):
    """Walk a dotted key; None when any step is missing."""
    cur = inner
    for part in dotted.split("."):
        if not isinstance(cur, dict) or part not in cur:
            return None
        cur = cur[part]
    return cur


def _require(inner: dict, dotted: str, path: str):
    """Fetch a gated metric by dotted key, or raise the NAMED-key
    GateError (exit 2) — never a KeyError traceback."""
    val = _lookup(inner, dotted)
    if val is None:
        raise GateError(
            f"{path}: artifact lacks gated metric '{dotted}'")
    return val


def extract(doc: dict, path: str = "<artifact>") -> dict:
    """Normalize an artifact into {name, value, unit, higher_better,
    vs_baseline?, slo_miss_rate?}. Raises GateError for unusable
    artifacts."""
    if doc.get("rc") not in (None, 0):
        raise GateError(f"{path}: recorded rc={doc.get('rc')} "
                        "(failed round — not comparable)")
    inner = doc.get("parsed", doc)
    if not isinstance(inner, dict):
        raise GateError(f"{path}: no parsed metric")
    if inner.get("mode") == "serve" or ("warm" in inner
                                        and "cold" in inner):
        warm = inner.get("warm") or {}
        value = warm.get("seq_p50_s", warm.get("p50_s"))
        if not value:
            raise GateError(
                f"{path}: artifact lacks gated metric 'warm.seq_p50_s'")
        out = {"name": "serve warm seq p50", "value": float(value),
               "unit": "s", "higher_better": False}
        miss = _lookup(inner, "slo.miss_rate")
        if miss is not None:
            out["slo_miss_rate"] = float(miss)
        # fleet-mode observability overhead (servebench --fleet): the
        # replicas' scrape-answering time as a % of the wave
        overhead = _lookup(inner, "fleet.scrape_overhead_pct")
        if overhead is not None:
            out["scrape_overhead_pct"] = float(overhead)
        # identity-audit sentinel cost (servebench --audit-rate): the
        # measured A/B wall delta, plus the mismatch count that must
        # stay zero on a clean workload
        audit_ov = _lookup(inner, "audit.overhead_pct")
        if audit_ov is not None:
            out["audit_overhead_pct"] = float(audit_ov)
        audit_mism = _lookup(inner, "audit.mismatches")
        if audit_mism is not None:
            out["audit_mismatches"] = float(audit_mism)
        # latency-tail metrics (continuous-batching era): gated
        # absolutely via --p99-max / --ttfb-p50-max and relatively
        # against the --against reference when both artifacts carry them
        for key, dotted in (("p99_s", "warm.p99_s"),
                            ("ttfb_p50_s", "warm.ttfb_p50_s")):
            val = _lookup(inner, dotted)
            if val is not None:
                out[key] = float(val)
        if isinstance(inner.get("mesh"), dict):
            out["mesh"] = inner["mesh"]
        return out
    if inner.get("mode") == "router":
        # servebench --router artifact: jobs/s through the shard-aware
        # router at the highest swept replica count, HIGHER is better.
        # No implicit baseline (the sweep is its own comparison) — the
        # router block's identity/requeue/scaling gates carry the
        # verdict; --against another router artifact adds the relative
        # throughput gate.
        value = _lookup(inner, "router.jobs_per_s")
        if value is None:
            raise GateError(
                f"{path}: artifact lacks gated metric "
                "'router.jobs_per_s'")
        out = {"name": "router jobs/s", "value": float(value),
               "unit": "jobs/sec", "higher_better": True,
               "kind": "router"}
        # distributed-trace plane cost (the traced-vs-untraced A/B the
        # router bench runs at its top count): gated absolutely at the
        # <2% observability budget via trace_checks
        trace_ov = _lookup(inner, "trace.overhead_pct")
        if trace_ov is not None:
            out["trace_overhead_pct"] = float(trace_ov)
        if isinstance(inner.get("mesh"), dict):
            out["mesh"] = inner["mesh"]
        return out
    if inner.get("mode") == "rounds":
        # servebench --rounds artifact: the round-2+ speedup of the
        # content-addressed window cache, HIGHER is better. No implicit
        # baseline (the cache-off arm inside the artifact IS the
        # comparison) — the cache block's identity/hit-rate gates carry
        # the verdict; --round2-speedup-min adds the absolute floor.
        value = _lookup(inner, "rounds.round2_speedup_x")
        if value is None:
            raise GateError(
                f"{path}: artifact lacks gated metric "
                "'rounds.round2_speedup_x'")
        out = {"name": "rounds round-2+ cache speedup",
               "value": float(value), "unit": "x",
               "higher_better": True, "kind": "rounds"}
        if isinstance(inner.get("mesh"), dict):
            out["mesh"] = inner["mesh"]
        return out
    if inner.get("mode") == "fragment":
        # servebench --fragment artifact: fragment-correction jobs/s
        # through the serve plane, HIGHER is better. No implicit
        # baseline (the contig wave inside the artifact IS the
        # comparison) — the fragment block's identity/vs-contig gates
        # carry the verdict; --fragment-jobs-min adds the absolute
        # floor; --against another fragment artifact adds the relative
        # throughput gate.
        value = _lookup(inner, "fragment.jobs_per_s")
        if value is None:
            raise GateError(
                f"{path}: artifact lacks gated metric "
                "'fragment.jobs_per_s'")
        out = {"name": "fragment jobs/s", "value": float(value),
               "unit": "jobs/sec", "higher_better": True,
               "kind": "fragment"}
        if isinstance(inner.get("mesh"), dict):
            out["mesh"] = inner["mesh"]
        return out
    if inner.get("mode") == "flood":
        # servebench --flood artifact: gold-tenant p99 under a
        # free-tenant flood with preemption, as a ratio over the idle
        # fabric's gold p99 — LOWER is better (1.0 = perfectly flat).
        # No implicit baseline (the idle arm inside the artifact IS
        # the comparison) — the qos block's absolute gates carry the
        # verdict; --against another flood artifact adds the relative
        # flatness gate.
        value = _lookup(inner, "qos.gold_p99_flat")
        if value is None:
            raise GateError(
                f"{path}: artifact lacks gated metric "
                "'qos.gold_p99_flat'")
        out = {"name": "flood gold p99 flatness", "value": float(value),
               "unit": "x", "higher_better": False, "kind": "flood"}
        if isinstance(inner.get("mesh"), dict):
            out["mesh"] = inner["mesh"]
        return out
    if inner.get("mode") == "ramp":
        # servebench --ramp artifact: gold p99 over the 1x->10x ramp
        # as a ratio over the idle 1-replica p99 — LOWER is better
        # (1.0 = the autoscaler held latency perfectly flat). No
        # implicit baseline (the idle arm inside the artifact IS the
        # comparison) — the autoscale block's absolute gates carry the
        # verdict; --against another ramp artifact adds the relative
        # flatness gate.
        value = _lookup(inner, "autoscale.gold_p99_flat")
        if value is None:
            raise GateError(
                f"{path}: artifact lacks gated metric "
                "'autoscale.gold_p99_flat'")
        out = {"name": "ramp gold p99 flatness", "value": float(value),
               "unit": "x", "higher_better": False, "kind": "ramp"}
        if isinstance(inner.get("mesh"), dict):
            out["mesh"] = inner["mesh"]
        return out
    if inner.get("mode") == "synth":
        # synthbench --json artifact: windows_per_s, HIGHER is better.
        # No implicit baseline exists for it (the published BASELINE
        # numbers measure the reference sample, a different workload) —
        # gate it absolutely (--windows-per-s-min) and/or against a
        # prior synth artifact (--against).
        value = _lookup(inner, "synth.windows_per_s")
        if value is None:
            raise GateError(
                f"{path}: artifact lacks gated metric "
                "'synth.windows_per_s'")
        out = {"name": "synthbench windows/s", "value": float(value),
               "unit": "windows/sec", "higher_better": True,
               "kind": "synth"}
        # dispatch-loop block (fused single-launch era): the measured
        # host-overhead fraction, gated absolutely via --host-frac-max
        hf = _lookup(inner, "fused.host_frac")
        if hf is not None:
            out["host_frac"] = float(hf)
        if isinstance(inner.get("mesh"), dict):
            out["mesh"] = inner["mesh"]
        return out
    if inner.get("unit") == "windows/sec":
        metric = str(inner.get("metric", ""))
        value = float(inner.get("value") or 0.0)
        if not value or metric.endswith("_failed"):
            raise GateError(f"{path}: failed/zero bench metric")
        out = {"name": metric, "value": value, "unit": "windows/sec",
               "higher_better": True}
        if inner.get("vs_baseline"):
            out["vs_baseline"] = float(inner["vs_baseline"])
        if isinstance(inner.get("mesh"), dict):
            out["mesh"] = inner["mesh"]
        return out
    raise GateError(f"{path}: unrecognized artifact shape "
                    f"(keys {sorted(inner)[:8]})")


def _round_number(path: str) -> int:
    m = re.search(r"_r(\d+)\.json$", os.path.basename(path))
    return int(m.group(1)) if m else -1


def find_artifacts(dirname: str) -> list[str]:
    """BENCH_r*.json in round order (oldest first)."""
    paths = glob.glob(os.path.join(dirname, "BENCH_r*.json"))
    return sorted(paths, key=_round_number)


def resolve_baseline(cand: dict, args, candidate_path: str) -> tuple:
    """-> (reference_value, description, reference_extract_or_None).
    The third element is the full extract() of the reference artifact
    when one exists (the --against paths) — the latency-tail metrics
    gate round-over-round against it. See module docstring."""
    if args.ref_value is not None:
        return float(args.ref_value), "explicit --ref-value", None
    if args.against:
        if args.against == "auto":
            prior = [p for p in find_artifacts(args.dir)
                     if _round_number(p) < _round_number(candidate_path)]
            for path in reversed(prior):
                try:
                    ref = extract(load_artifact(path), path)
                except GateError:
                    continue
                if ref["higher_better"] == cand["higher_better"]:
                    return ref["value"], os.path.basename(path), ref
            raise GateError("--against auto: no usable prior artifact")
        ref = extract(load_artifact(args.against), args.against)
        if ref["higher_better"] != cand["higher_better"]:
            raise GateError("--against artifact measures a different "
                            "direction than the candidate")
        return ref["value"], os.path.basename(args.against), ref
    baseline_path = os.path.join(args.dir, "BASELINE.json")
    if cand.get("kind") == "router":
        # a replica-count sweep is its own comparison point; the
        # router block's absolute gates carry the verdict
        raise GateError("router artifact has no implicit baseline "
                        "(use --router-scaling-min and/or --against)")
    if cand.get("kind") == "rounds":
        # the cache-off arm inside the artifact is the comparison
        # point; the cache block's absolute gates carry the verdict
        raise GateError("rounds artifact has no implicit baseline "
                        "(use --round2-speedup-min and/or --against)")
    if cand.get("kind") == "fragment":
        # the contig wave inside the artifact is the comparison point;
        # the fragment block's absolute gates carry the verdict
        raise GateError("fragment artifact has no implicit baseline "
                        "(use --fragment-jobs-min and/or --against)")
    if cand.get("kind") == "flood":
        # the idle-fabric arm inside the artifact is the comparison
        # point; the qos block's absolute gates carry the verdict
        raise GateError("flood artifact has no implicit baseline "
                        "(use --doomed-abort-min and/or --against)")
    if cand.get("kind") == "ramp":
        # the idle 1-replica arm inside the artifact is the comparison
        # point; the autoscale block's absolute gates carry the verdict
        raise GateError("ramp artifact has no implicit baseline "
                        "(use --ramp-p99-flat-max and/or --against)")
    if cand.get("kind") == "synth":
        # a published sample-workload baseline is not comparable with a
        # synthetic-scale run; synth artifacts gate absolutely and/or
        # against an explicit prior synth artifact only
        raise GateError("synth artifact has no implicit baseline "
                        "(use --windows-per-s-min and/or --against)")
    if os.path.isfile(baseline_path):
        published = (load_artifact(baseline_path).get("published")
                     or {})
        if published.get("windows_per_sec") and cand["higher_better"]:
            return (float(published["windows_per_sec"]),
                    "BASELINE.json published", None)
    if cand.get("vs_baseline"):
        # bench.py's own comparison point: value / vs_baseline is the
        # reference-CPU windows/s every artifact is ratioed against
        return (cand["value"] / cand["vs_baseline"],
                "reference-CPU baseline (value/vs_baseline)", None)
    raise GateError("no baseline: BASELINE.json publishes no "
                    "windows_per_sec and the artifact carries no "
                    "vs_baseline (use --ref-value or --against)")


def gate(candidate: float, reference: float, tolerance_pct: float,
         higher_better: bool) -> tuple[bool, float]:
    """-> (ok, delta_pct). delta_pct is signed improvement: positive =
    better than the reference, whatever the metric direction."""
    if reference <= 0:
        raise GateError(f"non-positive reference value {reference}")
    if higher_better:
        delta = (candidate / reference - 1.0) * 100.0
    else:
        delta = (reference / candidate - 1.0) * 100.0
    return delta >= -abs(tolerance_pct), delta


def slo_checks(doc: dict, cand: dict, args,
               candidate_path: str) -> list[tuple[str, float, float]]:
    """Absolute SLO gates for serve artifacts: (name, value, limit)
    triples. Gated when the artifact carries the metric OR the operator
    requested the limit explicitly — and an explicitly-requested gate
    over an artifact missing the metric is a named-key broken gate."""
    explicit = args.slo_miss_rate is not None
    if cand["higher_better"]:
        if explicit:
            # the operator DEMANDED an SLO gate; a bench artifact
            # cannot satisfy it — broken gate, never a silent pass
            raise GateError(
                f"{candidate_path}: artifact lacks gated metric "
                "'slo.miss_rate' (bench artifacts carry no slo view; "
                "--slo-miss-rate gates servebench artifacts)")
        return []
    inner = doc.get("parsed", doc)
    if not explicit and "slo_miss_rate" not in cand:
        return []
    if explicit and "slo_miss_rate" not in cand:
        _require(inner, "slo.miss_rate", candidate_path)
    limit = args.slo_miss_rate if explicit else 0.0
    return [("slo miss-rate", cand["slo_miss_rate"], limit)]


def latency_checks(cand: dict, ref: dict | None, args,
                   candidate_path: str) -> list[tuple]:
    """p99 / ttfb gates for serve artifacts: (name, value, limit,
    kind) quadruples. Each metric gates ABSOLUTELY when its --*-max
    limit was requested (a requested limit over an artifact missing the
    metric is a named-key broken gate, exit 2 — the slo.miss_rate
    convention) and RELATIVELY against the --against reference when
    both artifacts carry it (prior-round tail-latency regression)."""
    checks: list[tuple] = []
    for key, dotted, limit in (
            ("p99_s", "warm.p99_s", args.p99_max),
            ("ttfb_p50_s", "warm.ttfb_p50_s", args.ttfb_p50_max)):
        if limit is not None:
            if cand["higher_better"]:
                raise GateError(
                    f"{candidate_path}: artifact lacks gated metric "
                    f"'{dotted}' (bench artifacts carry no serve "
                    "latency view)")
            if key not in cand:
                raise GateError(
                    f"{candidate_path}: artifact lacks gated metric "
                    f"'{dotted}'")
            checks.append((dotted, cand[key], limit, "absolute"))
        if (ref is not None and key in cand and key in ref
                and ref[key] > 0):
            allowed = ref[key] * (1.0 + abs(args.tolerance_pct) / 100.0)
            checks.append((dotted, cand[key], allowed,
                           f"vs prior {ref[key]:g}s"))
    return checks


def check_mesh_comparable(cand: dict, ref: dict | None,
                          ref_desc: str) -> None:
    """Refuse cross-mesh comparisons: an artifact measured on 1 chip vs
    one measured on 8 (or at different serve worker-lane counts) is a
    different machine, not a perf delta. Only enforced when BOTH
    artifacts carry a mesh block (older artifacts predate it)."""
    cm = cand.get("mesh")
    rm = (ref or {}).get("mesh")
    if not cm or not rm:
        return
    for key in ("n_devices", "worker_lanes"):
        a, b = cm.get(key), rm.get(key)
        if a is not None and b is not None and a != b:
            raise GateError(
                f"cross-mesh comparison refused: candidate "
                f"mesh.{key}={a} vs reference ({ref_desc}) "
                f"mesh.{key}={b} — re-measure on the same mesh or "
                "pass --ref-value")


def scale_checks(doc: dict, args,
                 candidate_path: str) -> list[tuple[str, bool, str]]:
    """Mesh-scaling gates for synthbench --scale-curve artifacts:
    (name, ok, detail) triples. Gated whenever the artifact carries a
    `scale` block (the slo.miss_rate convention) or the operator passed
    --scale-balance-max explicitly — and an explicit request over an
    artifact without the block is a named-key broken gate. Per point
    with more than one device: per-shard useful-cell balance
    (max/min <= the limit, default 1.5) and the tail-batch padded-cell
    fraction STRICTLY below the full-mesh-rounding baseline (the
    sub-mesh dispatch win must be real, not rounding noise); plus the
    curve's byte-identity flag."""
    explicit = args.scale_balance_max is not None
    inner = doc.get("parsed", doc)
    scale = inner.get("scale") if isinstance(inner, dict) else None
    if not isinstance(scale, dict) or not scale.get("curve"):
        if explicit:
            raise GateError(
                f"{candidate_path}: artifact lacks gated metric "
                "'scale.curve' (--scale-balance-max gates synthbench "
                "--scale-curve artifacts)")
        return []
    limit = args.scale_balance_max if explicit else 1.5
    identical = bool(scale.get("identical"))
    checks = [("scale.identical", identical,
               "byte-identical FASTA across mesh sizes" if identical
               else "FASTA DIVERGED across mesh sizes")]
    for pt in scale["curve"]:
        n = pt.get("n_devices")
        if not n or n <= 1:
            continue  # 1-device points have no shards and no rounding
        bal = pt.get("shard_balance")
        if bal is not None:
            checks.append((f"scale.shard_balance[{n}dev]",
                           bal <= limit, f"{bal:g} <= {limit:g}"))
        elif pt.get("shard_useful"):
            # shards were recorded but the balance is undefined: some
            # shard saw ZERO useful cells — the worst imbalance, which
            # must fail the gate rather than silently skip it
            checks.append((f"scale.shard_balance[{n}dev]", False,
                           "a shard recorded zero useful cells "
                           "(balance undefined = total imbalance)"))
        pf = pt.get("padded_frac")
        pfm = pt.get("padded_frac_full_mesh")
        if pf is not None and pfm is not None:
            checks.append((f"scale.padded_frac[{n}dev]", pf < pfm,
                           f"{pf:g} < full-mesh baseline {pfm:g}"
                           + ("" if pf < pfm else
                              " (equal = no sub-mesh tail was "
                              "dispatched; use a workload whose batch "
                              "counts aren't exact lane multiples)")))
    return checks


def router_checks(doc: dict, args,
                  candidate_path: str) -> list[tuple[str, bool, str]]:
    """Replicated-serve gates for servebench --router artifacts:
    (name, ok, detail) triples. Whenever the artifact carries a
    `router` block: `router.identical` must be true (the routed merge
    must reproduce a direct single-replica submit byte-for-byte) and
    `router.requeues` must be zero (a requeue on the healthy bench
    fleet means a replica dropped mid-shard). `--router-scaling-min X`
    additionally gates `router.scaling_x` (jobs/s at the highest swept
    replica count over jobs/s at 1) >= X, and is mandatory once
    requested — an artifact without the key exits 2 naming it.
    `--range-scaling-min X` gates `router.range_scaling_x` (the
    single-job window-range-sharding speedup: sequential job wall at
    1 replica over the wall at the highest swept count) >= X the same
    way — mandatory once requested, rc 2 naming the dotted key when
    the artifact never range-sharded."""
    explicit = args.router_scaling_min is not None
    explicit_range = args.range_scaling_min is not None
    inner = doc.get("parsed", doc)
    router = inner.get("router") if isinstance(inner, dict) else None
    if not isinstance(router, dict):
        if explicit:
            raise GateError(
                f"{candidate_path}: artifact lacks gated metric "
                "'router.scaling_x' (--router-scaling-min gates "
                "servebench --router artifacts)")
        if explicit_range:
            raise GateError(
                f"{candidate_path}: artifact lacks gated metric "
                "'router.range_scaling_x' (--range-scaling-min gates "
                "servebench --router artifacts)")
        return []
    identical = bool(router.get("identical"))
    checks = [("router.identical", identical,
               "routed FASTA byte-identical to a direct submit"
               if identical else
               "routed FASTA DIVERGED from a direct submit")]
    requeues = router.get("requeues")
    if requeues is not None:
        checks.append(("router.requeues", requeues == 0,
                       f"{requeues} == 0"
                       + ("" if requeues == 0 else
                          " (a replica dropped mid-shard on the "
                          "healthy bench fleet)")))
    if explicit:
        scaling = _lookup(inner, "router.scaling_x")
        if scaling is None:
            raise GateError(
                f"{candidate_path}: artifact lacks gated metric "
                "'router.scaling_x'")
        limit = float(args.router_scaling_min)
        checks.append(("router.scaling_x", float(scaling) >= limit,
                       f"{scaling:g} >= {limit:g}"))
    if explicit_range:
        rscaling = _lookup(inner, "router.range_scaling_x")
        if rscaling is None:
            raise GateError(
                f"{candidate_path}: artifact lacks gated metric "
                "'router.range_scaling_x' (the sweep's top point "
                "never window-range-sharded — use a --contigs 1 "
                "workload with 2+ replicas)")
        limit = float(args.range_scaling_min)
        checks.append(("router.range_scaling_x",
                       float(rscaling) >= limit,
                       f"{rscaling:g} >= {limit:g}"))
    return checks


def cache_checks(doc: dict, args,
                 candidate_path: str) -> list[tuple[str, bool, str]]:
    """Window-cache gates for servebench --rounds artifacts:
    (name, ok, detail) triples. Whenever the artifact carries a
    `cache` block: `cache.identical` must be true (cached rounds must
    reproduce the cache-off bytes exactly — the cache is a dispatch
    skip, never an answer change), `cache.hit_rate` must be NONZERO
    when recorded (an artifact whose cache never engaged measured
    nothing), and `audit.mismatches` must be zero when the sentinel
    rode the cached run. `--round2-speedup-min X` additionally gates
    `rounds.round2_speedup_x` >= X, mandatory once requested — an
    artifact without the key exits 2 naming it."""
    explicit = args.round2_speedup_min is not None
    inner = doc.get("parsed", doc)
    cache = inner.get("cache") if isinstance(inner, dict) else None
    if not isinstance(cache, dict):
        if explicit:
            raise GateError(
                f"{candidate_path}: artifact lacks gated metric "
                "'rounds.round2_speedup_x' (--round2-speedup-min "
                "gates servebench --rounds artifacts)")
        return []
    identical = bool(cache.get("identical"))
    checks = [("cache.identical", identical,
               "cached rounds FASTA byte-identical to cache-off"
               if identical else
               "cached rounds FASTA DIVERGED from the cache-off "
               "bytes")]
    hit_rate = cache.get("hit_rate")
    if hit_rate is not None:
        # the first cached pass may legitimately sit near zero on a
        # non-converging workload; the resubmit rate is the floor that
        # proves the cache engaged at all
        resub = _lookup(cache, "resubmit.hit_rate")
        best = max(float(hit_rate), float(resub or 0.0))
        checks.append(("cache.hit_rate", best > 0.0,
                       f"{best:g} > 0"
                       + ("" if best > 0.0 else
                          " (the cache never engaged)")))
    mism = _lookup(inner, "audit.mismatches")
    if mism is not None:
        checks.append(("audit.mismatches", float(mism) == 0.0,
                       f"{mism:g} == 0"
                       + ("" if not mism else
                          " (sentinel mismatch over cached rounds = "
                          "a poisoned entry reached output)")))
    if explicit:
        speedup = _lookup(inner, "rounds.round2_speedup_x")
        if speedup is None:
            raise GateError(
                f"{candidate_path}: artifact lacks gated metric "
                "'rounds.round2_speedup_x'")
        limit = float(args.round2_speedup_min)
        checks.append(("rounds.round2_speedup_x",
                       float(speedup) >= limit,
                       f"{speedup:g} >= {limit:g}"))
    return checks


def fragment_checks(doc: dict, args,
                    candidate_path: str) -> list[tuple[str, bool, str]]:
    """Fragment-correction gates for servebench --fragment artifacts:
    (name, ok, detail) triples. Whenever the artifact carries a
    `fragment` block: `fragment.identical` must be true (the serve
    fragment path must reproduce the solo kF bytes exactly — serving
    is a transport, never an answer change), and `fragment.vs_contig_x`
    must exceed 1 when recorded (fragment jobs are per-read-pile
    corrections with no contig assembly; a rate at or below the contig
    wave means the fragment plane added overhead instead of removing
    work). `--fragment-jobs-min X` additionally gates
    `fragment.jobs_per_s` >= X, mandatory once requested — an artifact
    without the key exits 2 naming it."""
    explicit = args.fragment_jobs_min is not None
    inner = doc.get("parsed", doc)
    frag = inner.get("fragment") if isinstance(inner, dict) else None
    if not isinstance(frag, dict):
        if explicit:
            raise GateError(
                f"{candidate_path}: artifact lacks gated metric "
                "'fragment.jobs_per_s' (--fragment-jobs-min gates "
                "servebench --fragment artifacts)")
        return []
    identical = bool(frag.get("identical"))
    checks = [("fragment.identical", identical,
               "serve fragment FASTA byte-identical to the solo kF run"
               if identical else
               "serve fragment FASTA DIVERGED from the solo kF bytes")]
    vs_contig = frag.get("vs_contig_x")
    if vs_contig is not None:
        checks.append(("fragment.vs_contig_x", float(vs_contig) > 1.0,
                       f"{vs_contig:g} > 1"
                       + ("" if float(vs_contig) > 1.0 else
                          " (fragment jobs/s must clear the contig "
                          "wave's rate)")))
    if explicit:
        jps = _lookup(inner, "fragment.jobs_per_s")
        if jps is None:
            raise GateError(
                f"{candidate_path}: artifact lacks gated metric "
                "'fragment.jobs_per_s'")
        limit = float(args.fragment_jobs_min)
        checks.append(("fragment.jobs_per_s", float(jps) >= limit,
                       f"{jps:g} >= {limit:g}"))
    return checks


def qos_checks(doc: dict, args,
               candidate_path: str) -> list[tuple[str, bool, str]]:
    """Preemptive-QoS gates for servebench --flood artifacts:
    (name, ok, detail) triples. Whenever the artifact carries a `qos`
    block: `qos.gold_p99_flat` (gold p99 under flood-with-preemption
    over gold p99 idle) gates ABSOLUTELY at the default 2.0 — gold
    latency must stay flat, not merely better than the no-preemption
    arm; `--gold-p99-flat-max` overrides the limit and makes the gate
    mandatory (an artifact without the key exits 2 naming it).
    `--doomed-abort-min X` additionally gates
    `qos.doomed_abort_saved_s` (EMA-predicted device seconds the
    admission-time deadline-aborts saved) >= X, mandatory once
    requested — an artifact without the key exits 2 naming it."""
    explicit_flat = args.gold_p99_flat_max is not None
    explicit_doomed = args.doomed_abort_min is not None
    inner = doc.get("parsed", doc)
    qos = inner.get("qos") if isinstance(inner, dict) else None
    if not isinstance(qos, dict):
        if explicit_flat:
            raise GateError(
                f"{candidate_path}: artifact lacks gated metric "
                "'qos.gold_p99_flat' (--gold-p99-flat-max gates "
                "servebench --flood artifacts)")
        if explicit_doomed:
            raise GateError(
                f"{candidate_path}: artifact lacks gated metric "
                "'qos.doomed_abort_saved_s' (--doomed-abort-min gates "
                "servebench --flood artifacts)")
        return []
    checks: list[tuple[str, bool, str]] = []
    flat = qos.get("gold_p99_flat")
    if flat is None:
        if explicit_flat:
            raise GateError(
                f"{candidate_path}: artifact lacks gated metric "
                "'qos.gold_p99_flat'")
    else:
        limit = (args.gold_p99_flat_max if explicit_flat else 2.0)
        ok = float(flat) <= limit
        checks.append(("qos.gold_p99_flat", ok,
                       f"{flat:g} <= {limit:g}"
                       + ("" if ok else
                          " (gold p99 under the flood is NOT flat vs "
                          "the idle fabric — preemption failed to "
                          "isolate the gold tenant)")))
    if explicit_doomed:
        saved = _lookup(inner, "qos.doomed_abort_saved_s")
        if saved is None:
            raise GateError(
                f"{candidate_path}: artifact lacks gated metric "
                "'qos.doomed_abort_saved_s'")
        limit = float(args.doomed_abort_min)
        ok = float(saved) >= limit
        checks.append(("qos.doomed_abort_saved_s", ok,
                       f"{saved:g} >= {limit:g}"
                       + ("" if ok else
                          " (the speculative deadline-abort saved "
                          "less device time than the floor)")))
    return checks


def autoscale_checks(doc: dict, args,
                     candidate_path: str) -> list[tuple[str, bool, str]]:
    """Elastic-fleet gates for servebench --ramp artifacts:
    (name, ok, detail) triples. Whenever the artifact carries an
    `autoscale` block: `autoscale.jobs_lost` must be ZERO (a job lost
    across a scale-up or scale-down is the race the unroute-then-drain
    handshake exists to prevent — never acceptable noise) and
    `autoscale.gold_p99_flat` (gold p99 over the 1x->10x ramp divided
    by the idle 1-replica p99) gates ABSOLUTELY at the default 2.0 —
    the loop must hold latency flat, not merely absorb some load;
    `--ramp-p99-flat-max` overrides the limit and makes the gate
    mandatory (an artifact without the key exits 2 naming it)."""
    explicit = args.ramp_p99_flat_max is not None
    inner = doc.get("parsed", doc)
    block = inner.get("autoscale") if isinstance(inner, dict) else None
    if not isinstance(block, dict):
        if explicit:
            raise GateError(
                f"{candidate_path}: artifact lacks gated metric "
                "'autoscale.gold_p99_flat' (--ramp-p99-flat-max gates "
                "servebench --ramp artifacts)")
        return []
    checks: list[tuple[str, bool, str]] = []
    lost = block.get("jobs_lost")
    if lost is not None:
        ok = float(lost) == 0.0
        checks.append(("autoscale.jobs_lost", ok,
                       f"{lost:g} == 0"
                       + ("" if ok else
                          " (a job was LOST across a scale event — "
                          "the drain/requeue handshake failed)")))
    flat = block.get("gold_p99_flat")
    if flat is None:
        if explicit:
            raise GateError(
                f"{candidate_path}: artifact lacks gated metric "
                "'autoscale.gold_p99_flat'")
    else:
        limit = args.ramp_p99_flat_max if explicit else 2.0
        ok = float(flat) <= limit
        checks.append(("autoscale.gold_p99_flat", ok,
                       f"{flat:g} <= {limit:g}"
                       + ("" if ok else
                          " (gold p99 under the ramp is NOT flat vs "
                          "the idle floor — the autoscaler failed to "
                          "absorb the offered load)")))
    return checks


def fused_checks(cand: dict, args,
                 candidate_path: str) -> list[tuple[str, float, float]]:
    """Host-overhead gate for artifacts carrying a `fused` block
    (synthbench with device consensus armed): `fused.host_frac` — the
    measured host-side fraction of the polish wall, the number the
    fused dispatch loop exists to shrink — gates ABSOLUTELY. Gated at
    the default limit whenever the artifact carries the key (the
    slo.miss_rate convention); passing --host-frac-max makes it
    mandatory — an artifact without the key then exits 2 naming it.
    The windows/s floor stays mandatory alongside (wps_floor_check):
    a fused-block artifact gates BOTH the throughput floor and the
    overhead fraction when both are requested."""
    explicit = args.host_frac_max is not None
    if "host_frac" not in cand:
        if explicit:
            raise GateError(
                f"{candidate_path}: artifact lacks gated metric "
                "'fused.host_frac' (--host-frac-max gates synthbench "
                "artifacts with a fused block)")
        return []
    limit = args.host_frac_max if explicit else 0.75
    return [("fused.host_frac", cand["host_frac"], limit)]


def fleet_checks(cand: dict, args,
                 candidate_path: str) -> list[tuple[str, float, float]]:
    """Scrape/exemplar overhead gate for servebench --fleet artifacts:
    `fleet.scrape_overhead_pct` — the replicas' time answering the
    aggregator as a percentage of the measured wave — gates ABSOLUTELY
    at the established observability budget (<2%, the same bound the
    flight recorder and journal were held to). Gated at the default
    whenever the artifact carries the key (the slo.miss_rate
    convention); `--scrape-overhead-max` makes it mandatory — an
    artifact without the key then exits 2 naming it."""
    explicit = args.scrape_overhead_max is not None
    if "scrape_overhead_pct" not in cand:
        if explicit:
            raise GateError(
                f"{candidate_path}: artifact lacks gated metric "
                "'fleet.scrape_overhead_pct' (--scrape-overhead-max "
                "gates servebench --fleet artifacts)")
        return []
    limit = args.scrape_overhead_max if explicit else 2.0
    return [("fleet.scrape_overhead_pct", cand["scrape_overhead_pct"],
             limit)]


def audit_checks(cand: dict, args,
                 candidate_path: str) -> list[tuple[str, float, float]]:
    """Identity-audit gates for servebench --audit-rate artifacts:
    `audit.overhead_pct` (the measured audited-vs-muted wall delta)
    gates ABSOLUTELY at the established <2% observability budget —
    default whenever the artifact carries the key (the slo.miss_rate
    convention), mandatory via `--audit-overhead-max` (an artifact
    without it then exits 2 naming the dotted key) — and
    `audit.mismatches` gates at ZERO whenever the block is present: a
    sentinel mismatch on the clean bench workload is silent data
    corruption, never an acceptable perf trade."""
    explicit = args.audit_overhead_max is not None
    if "audit_overhead_pct" not in cand:
        if explicit:
            raise GateError(
                f"{candidate_path}: artifact lacks gated metric "
                "'audit.overhead_pct' (--audit-overhead-max gates "
                "servebench --audit-rate artifacts)")
        return []
    limit = args.audit_overhead_max if explicit else 2.0
    checks = [("audit.overhead_pct", cand["audit_overhead_pct"],
               limit)]
    if "audit_mismatches" in cand:
        checks.append(("audit.mismatches", cand["audit_mismatches"],
                       0.0))
    return checks


def trace_checks(cand: dict, args,
                 candidate_path: str) -> list[tuple[str, float, float]]:
    """Distributed-trace plane gate for servebench --router artifacts:
    `trace.overhead_pct` (the traced-vs-untraced sequential-job A/B
    the router bench runs at its top replica count — client spans,
    router spans, per-replica trace_pull and the clock-chained merge
    all armed) gates ABSOLUTELY at the established <2% observability
    budget — default whenever the artifact carries the key, mandatory
    via `--trace-overhead-max` (an artifact without it then exits 2
    naming the dotted key)."""
    explicit = args.trace_overhead_max is not None
    if "trace_overhead_pct" not in cand:
        if explicit:
            raise GateError(
                f"{candidate_path}: artifact lacks gated metric "
                "'trace.overhead_pct' (--trace-overhead-max gates "
                "servebench --router artifacts)")
        return []
    limit = args.trace_overhead_max if explicit else 2.0
    return [("trace.overhead_pct", cand["trace_overhead_pct"],
             limit)]


def wps_floor_check(cand: dict, args,
                    candidate_path: str) -> list[tuple[str, float, float]]:
    """Absolute windows/s floor (--windows-per-s-min): mandatory once
    requested — a candidate without a windows/sec metric (e.g. a serve
    artifact) is a named-key broken gate, exit 2 — so a kernel-plane
    regression fails CI the same way serve regressions do."""
    if args.windows_per_s_min is None:
        return []
    if not cand["higher_better"]:
        raise GateError(
            f"{candidate_path}: artifact lacks gated metric "
            "'synth.windows_per_s' (serve artifacts carry no "
            "windows/s; --windows-per-s-min gates synthbench/bench "
            "artifacts)")
    return [("windows/s floor", cand["value"],
             float(args.windows_per_s_min))]


def run(args) -> int:
    if args.artifact:
        candidate_path = args.artifact
    else:
        arts = find_artifacts(args.dir)
        if not arts:
            raise GateError(f"no BENCH_r*.json under {args.dir}")
        candidate_path = arts[-1]
    doc = load_artifact(candidate_path)
    cand = extract(doc, candidate_path)
    # the absolute windows/s floor resolves FIRST: a mandatory flag over
    # the wrong artifact shape must exit 2 naming the dotted key, not
    # trip over baseline resolution
    wps_checks = wps_floor_check(cand, args, candidate_path)
    try:
        reference, ref_desc, ref = resolve_baseline(cand, args,
                                                    candidate_path)
    except GateError:
        # a synth artifact gated only by its absolute floor needs no
        # baseline — but ONLY when no explicit baseline was requested:
        # a --against that fails to resolve (corrupt file, wrong
        # direction, no usable prior) must stay a broken gate, or the
        # requested relative comparison silently never runs
        if (cand.get("kind") == "synth"
                and args.windows_per_s_min is not None
                and not args.against):
            reference, ref_desc, ref = None, "", None
        elif cand.get("kind") == "router" and not args.against:
            # router artifacts always carry their own absolute gates
            # (identity + requeues, plus --router-scaling-min): no
            # baseline needed unless a relative --against was asked for
            reference, ref_desc, ref = None, "", None
        elif cand.get("kind") == "rounds" and not args.against:
            # rounds artifacts carry the cache-off arm internally:
            # identity + hit-rate gates (plus --round2-speedup-min)
            # are absolute, no external baseline required
            reference, ref_desc, ref = None, "", None
        elif cand.get("kind") == "fragment" and not args.against:
            # fragment artifacts carry the contig wave internally:
            # identity + vs-contig gates (plus --fragment-jobs-min)
            # are absolute, no external baseline required
            reference, ref_desc, ref = None, "", None
        elif cand.get("kind") == "flood" and not args.against:
            # flood artifacts carry the idle arm internally: the qos
            # block's flatness (plus --doomed-abort-min) gates are
            # absolute, no external baseline required
            reference, ref_desc, ref = None, "", None
        elif cand.get("kind") == "ramp" and not args.against:
            # ramp artifacts carry the idle 1-replica arm internally:
            # the autoscale block's jobs_lost/flatness gates are
            # absolute, no external baseline required
            reference, ref_desc, ref = None, "", None
        else:
            raise
    # mesh comparability resolves BEFORE any relative verdict prints: a
    # cross-mesh --against is a broken gate (rc 2 naming the key), never
    # a spurious PASS/FAIL
    if ref is not None:
        check_mesh_comparable(cand, ref, ref_desc)
    failures = 0
    if reference is not None:
        ok, delta = gate(cand["value"], reference, args.tolerance_pct,
                         cand["higher_better"])
        failures += 0 if ok else 1
        verdict = "PASS" if ok else "FAIL"
        print(f"[perfgate] {verdict}: {os.path.basename(candidate_path)} "
              f"{cand['name']} = {cand['value']:g} {cand['unit']} vs "
              f"{reference:g} ({ref_desc}): {delta:+.1f}% "
              f"(tolerance -{abs(args.tolerance_pct):g}%)",
              file=sys.stderr)
    for name, value, floor in wps_checks:
        check_ok = value >= floor
        failures += 0 if check_ok else 1
        print(f"[perfgate] {'PASS' if check_ok else 'FAIL'}: "
              f"{os.path.basename(candidate_path)} {name} = {value:g} "
              f"(min {floor:g})", file=sys.stderr)
    for name, value, limit in fused_checks(cand, args, candidate_path):
        check_ok = value <= limit
        failures += 0 if check_ok else 1
        print(f"[perfgate] {'PASS' if check_ok else 'FAIL'}: "
              f"{os.path.basename(candidate_path)} {name} = {value:g} "
              f"(limit {limit:g})", file=sys.stderr)
    for name, value, limit in fleet_checks(cand, args, candidate_path):
        check_ok = value <= limit
        failures += 0 if check_ok else 1
        print(f"[perfgate] {'PASS' if check_ok else 'FAIL'}: "
              f"{os.path.basename(candidate_path)} {name} = {value:g}% "
              f"(limit {limit:g}%)", file=sys.stderr)
    for name, value, limit in audit_checks(cand, args, candidate_path):
        check_ok = value <= limit
        failures += 0 if check_ok else 1
        print(f"[perfgate] {'PASS' if check_ok else 'FAIL'}: "
              f"{os.path.basename(candidate_path)} {name} = {value:g} "
              f"(limit {limit:g})", file=sys.stderr)
    for name, value, limit in trace_checks(cand, args, candidate_path):
        check_ok = value <= limit
        failures += 0 if check_ok else 1
        print(f"[perfgate] {'PASS' if check_ok else 'FAIL'}: "
              f"{os.path.basename(candidate_path)} {name} = {value:g}% "
              f"(limit {limit:g}%)", file=sys.stderr)
    for name, value, limit in slo_checks(doc, cand, args,
                                         candidate_path):
        check_ok = value <= limit
        failures += 0 if check_ok else 1
        print(f"[perfgate] {'PASS' if check_ok else 'FAIL'}: "
              f"{os.path.basename(candidate_path)} {name} = {value:g} "
              f"(limit {limit:g})", file=sys.stderr)
    for name, value, limit, kind in latency_checks(cand, ref, args,
                                                   candidate_path):
        check_ok = value <= limit
        failures += 0 if check_ok else 1
        print(f"[perfgate] {'PASS' if check_ok else 'FAIL'}: "
              f"{os.path.basename(candidate_path)} {name} = {value:g}s "
              f"(limit {limit:g}s, {kind})", file=sys.stderr)
    for name, check_ok, detail in router_checks(doc, args,
                                                candidate_path):
        failures += 0 if check_ok else 1
        print(f"[perfgate] {'PASS' if check_ok else 'FAIL'}: "
              f"{os.path.basename(candidate_path)} {name} ({detail})",
              file=sys.stderr)
    for name, check_ok, detail in qos_checks(doc, args,
                                             candidate_path):
        failures += 0 if check_ok else 1
        print(f"[perfgate] {'PASS' if check_ok else 'FAIL'}: "
              f"{os.path.basename(candidate_path)} {name} ({detail})",
              file=sys.stderr)
    for name, check_ok, detail in autoscale_checks(doc, args,
                                                   candidate_path):
        failures += 0 if check_ok else 1
        print(f"[perfgate] {'PASS' if check_ok else 'FAIL'}: "
              f"{os.path.basename(candidate_path)} {name} ({detail})",
              file=sys.stderr)
    for name, check_ok, detail in cache_checks(doc, args,
                                               candidate_path):
        failures += 0 if check_ok else 1
        print(f"[perfgate] {'PASS' if check_ok else 'FAIL'}: "
              f"{os.path.basename(candidate_path)} {name} ({detail})",
              file=sys.stderr)
    for name, check_ok, detail in fragment_checks(doc, args,
                                                  candidate_path):
        failures += 0 if check_ok else 1
        print(f"[perfgate] {'PASS' if check_ok else 'FAIL'}: "
              f"{os.path.basename(candidate_path)} {name} ({detail})",
              file=sys.stderr)
    for name, check_ok, detail in scale_checks(doc, args,
                                               candidate_path):
        failures += 0 if check_ok else 1
        print(f"[perfgate] {'PASS' if check_ok else 'FAIL'}: "
              f"{os.path.basename(candidate_path)} {name} ({detail})",
              file=sys.stderr)
    return 0 if not failures else 1


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="perf regression gate over bench/servebench "
                    "artifacts (see module docstring)")
    ap.add_argument("--dir", default=REPO,
                    help="directory holding BENCH_r*.json / "
                         "BASELINE.json (default: repo root)")
    ap.add_argument("--artifact", default=None,
                    help="candidate artifact (default: newest "
                         "BENCH_r*.json in --dir)")
    ap.add_argument("--against", default=None,
                    help="reference artifact path, or 'auto' for the "
                         "newest usable prior round")
    ap.add_argument("--ref-value", type=float, default=None,
                    help="explicit reference value (wins over "
                         "everything)")
    ap.add_argument("--tolerance-pct", type=float, default=10.0,
                    help="allowed regression in percent (default 10)")
    ap.add_argument("--windows-per-s-min", type=float, default=None,
                    help="absolute floor on the candidate's windows/s "
                         "(synthbench --json or bench artifacts); "
                         "mandatory once passed — a candidate without "
                         "the metric exits 2 naming the dotted key. "
                         "For synth artifacts this also makes the "
                         "relative gate optional (no implicit baseline "
                         "exists for synthetic workloads)")
    ap.add_argument("--host-frac-max", type=float, default=None,
                    help="absolute bound on the measured host-overhead "
                         "fraction of the polish wall "
                         "(fused.host_frac, synthbench artifacts with "
                         "device consensus armed; default: gate at "
                         "0.75 whenever the artifact carries the key; "
                         "passing a value makes the gate mandatory — "
                         "an artifact without it then exits 2 naming "
                         "the dotted key)")
    ap.add_argument("--slo-miss-rate", type=float, default=None,
                    help="allowed deadline-miss rate for servebench "
                         "artifacts (default: gate at 0.0 whenever the "
                         "artifact carries an slo view; passing a "
                         "value makes the gate mandatory — an artifact "
                         "without slo.miss_rate then exits 2)")
    ap.add_argument("--p99-max", type=float, default=None,
                    help="absolute bound in seconds on the servebench "
                         "wave p99 (warm.p99_s); mandatory once "
                         "passed — a candidate without the key exits "
                         "2. Also gated RELATIVELY (tolerance-pct) "
                         "against the --against reference whenever "
                         "both artifacts carry it")
    ap.add_argument("--ttfb-p50-max", type=float, default=None,
                    help="absolute bound in seconds on the servebench "
                         "time-to-first-byte p50 (warm.ttfb_p50_s); "
                         "same mandatory/relative semantics as "
                         "--p99-max")
    ap.add_argument("--audit-overhead-max", type=float, default=None,
                    help="absolute bound in PERCENT on the identity-"
                         "audit sentinel's measured overhead "
                         "(audit.overhead_pct, servebench --audit-rate "
                         "artifacts; default: gate at 2.0 whenever the "
                         "artifact carries the key; passing a value "
                         "makes the gate mandatory — an artifact "
                         "without it then exits 2 naming the dotted "
                         "key). Artifacts with an audit block are also "
                         "always gated on audit.mismatches == 0")
    ap.add_argument("--trace-overhead-max", type=float, default=None,
                    help="absolute bound in PERCENT on the distributed-"
                         "trace plane's measured cost "
                         "(trace.overhead_pct, the traced-vs-untraced "
                         "A/B in servebench --router artifacts; "
                         "default: gate at 2.0 whenever the artifact "
                         "carries the key; passing a value makes the "
                         "gate mandatory — an artifact without it then "
                         "exits 2 naming the dotted key)")
    ap.add_argument("--scrape-overhead-max", type=float, default=None,
                    help="absolute bound in PERCENT on the fleet "
                         "observability overhead "
                         "(fleet.scrape_overhead_pct, servebench "
                         "--fleet artifacts; default: gate at 2.0 "
                         "whenever the artifact carries the key; "
                         "passing a value makes the gate mandatory — "
                         "an artifact without it then exits 2 naming "
                         "the dotted key)")
    ap.add_argument("--router-scaling-min", type=float, default=None,
                    help="absolute floor on the router throughput "
                         "scaling factor (router.scaling_x: jobs/s at "
                         "the highest swept replica count over jobs/s "
                         "at 1, servebench --router artifacts); "
                         "mandatory once passed — an artifact without "
                         "the key exits 2 naming the dotted key. "
                         "Router artifacts are also always gated on "
                         "router.identical and router.requeues == 0 "
                         "whenever the block is present")
    ap.add_argument("--range-scaling-min", type=float, default=None,
                    help="absolute floor on the single-job window-"
                         "range-sharding speedup "
                         "(router.range_scaling_x: sequential job "
                         "wall at 1 replica over the wall at the "
                         "highest swept count, servebench --router "
                         "artifacts on a --contigs 1 workload); "
                         "mandatory once passed — an artifact without "
                         "the key exits 2 naming the dotted key")
    ap.add_argument("--ramp-p99-flat-max", type=float, default=None,
                    help="absolute bound on the ramp-mode gold-p99 "
                         "flatness ratio (autoscale.gold_p99_flat: "
                         "gold p99 over the 1x->10x Poisson ramp over "
                         "the idle 1-replica p99, servebench --ramp "
                         "artifacts; default: gate at 2.0 whenever "
                         "the artifact carries the key; passing a "
                         "value makes the gate mandatory — an "
                         "artifact without it then exits 2 naming the "
                         "dotted key). Ramp artifacts are also always "
                         "gated on autoscale.jobs_lost == 0 whenever "
                         "the block is present")
    ap.add_argument("--round2-speedup-min", type=float, default=None,
                    help="absolute floor on the window-cache round-2+ "
                         "speedup (rounds.round2_speedup_x: mean "
                         "no-cache round-2+ wall over mean cached "
                         "round-2+ wall, servebench --rounds "
                         "artifacts); mandatory once passed — an "
                         "artifact without the key exits 2 naming the "
                         "dotted key. Rounds artifacts are also always "
                         "gated on cache.identical, a nonzero "
                         "cache.hit_rate and audit.mismatches == 0 "
                         "whenever those keys are present")
    ap.add_argument("--fragment-jobs-min", type=float, default=None,
                    help="absolute floor on fragment-correction "
                         "throughput (fragment.jobs_per_s, servebench "
                         "--fragment artifacts); mandatory once passed "
                         "— an artifact without the key exits 2 naming "
                         "the dotted key. Fragment artifacts are also "
                         "always gated on fragment.identical (serve "
                         "bytes == solo kF bytes) and on "
                         "fragment.vs_contig_x > 1 whenever those keys "
                         "are present")
    ap.add_argument("--gold-p99-flat-max", type=float, default=None,
                    help="absolute bound on the flood-mode gold-p99 "
                         "flatness ratio (qos.gold_p99_flat: gold p99 "
                         "under flood-with-preemption over gold p99 "
                         "idle, servebench --flood artifacts; default: "
                         "gate at 2.0 whenever the artifact carries "
                         "the key; passing a value makes the gate "
                         "mandatory — an artifact without it then "
                         "exits 2 naming the dotted key)")
    ap.add_argument("--doomed-abort-min", type=float, default=None,
                    help="absolute floor in SECONDS on the device time "
                         "the speculative deadline-aborts saved "
                         "(qos.doomed_abort_saved_s, servebench "
                         "--flood artifacts); mandatory once passed — "
                         "an artifact without the key exits 2 naming "
                         "the dotted key")
    ap.add_argument("--scale-balance-max", type=float, default=None,
                    help="per-shard useful-cell balance bound (max/min) "
                         "for synthbench --scale-curve artifacts "
                         "(default: gate at 1.5 whenever the artifact "
                         "carries a scale block; passing a value makes "
                         "the gate mandatory — an artifact without "
                         "scale.curve then exits 2). The scale block "
                         "is also always gated on curve byte-identity "
                         "and on each multi-device point's padded-cell "
                         "fraction being strictly below its full-mesh-"
                         "rounding baseline")
    args = ap.parse_args(argv)
    try:
        return run(args)
    except GateError as exc:
        print(f"[perfgate] ERROR: {exc}", file=sys.stderr)
        return 2


if __name__ == "__main__":
    sys.exit(main())
