"""Serve-mode benchmark: warm server submits vs cold one-shot CLI runs.

Starts a `PolishServer` (warmed on the benchmark's own inputs, so job
shapes hit the warm jit caches exactly), submits N concurrent synthetic
jobs through `PolishClient`, and compares against N sequential COLD CLI
runs — fresh `python -m racon_tpu.cli` subprocesses, each paying
interpreter + import + engine construction + compile, which is precisely
the per-run tax the serve subsystem amortizes.

Two warm phases measure two different claims:

  - SEQUENTIAL warm submits (one at a time — the like-for-like twin of
    the sequential cold runs, same machine utilization): their p50 is
    the headline warm latency and must beat the cold p50;
  - a CONCURRENT wave of N submits: cross-job batch rounds, queue-wait
    vs execution breakdown, and batch occupancy — the multiplexing
    story (concurrent p50 embeds queueing on an oversubscribed host, so
    it is reported, not gated).

Exit status is the acceptance check: 0 only when sequential warm p50
beats cold p50, no warm job compiled anything (sched compile telemetry:
the warm path recompiles NOTHING), every warm job's FASTA equals the
cold CLI bytes, every wave job saw at least one live progress frame AND
one streamed `result_part` frame before its result (time-to-first-
progress and time-to-first-BYTE are reported as their own columns), and
the serve event journal — enabled for the measured run — passes its
consistency check (every job exactly one terminal state,
started/terminal pairs balanced). `--json PATH` writes the summary as a
bench-style artifact with `occupancy` / `metrics` / `slo` / `journal`
fields alongside the serve numbers (the same field names bench.py
publishes; tools/perfgate.py gates warm p50, p99, ttfb_p50 and
slo.miss_rate from it).

FLEET MODE (`--fleet N`): run N in-process server replicas, round-robin
the warm wave across them, and let the fleet aggregator (obs/fleet.py)
poll every replica's scrape+healthz MID-WAVE. The artifact gains a
`fleet` block — aggregator lag (poll wall) percentiles and the
scrape-overhead percentage — which tools/perfgate.py gates at the
established <2% observability budget.

ROUTER MODE (`--router N`): start N warm replicas behind the
shard-aware router (racon_tpu/serve/router.py) and sweep the same
concurrent wave through it at 1, 2, 4 ... replicas (capped at N). The
artifact becomes a `router` block — jobs/s per replica count, requeue
count (zero on a healthy fleet, any requeue fails the bench), the
router's merge overhead (job wall minus slowest-shard exec) and
byte-identity vs a direct single-replica submit — plus `scaling_x`
(jobs/s at N over jobs/s at 1), which tools/perfgate.py gates via
`router.identical` and `--router-scaling-min`. The block also
carries the routed time-to-first-part (`ttfb_s`) and, at the top
count, a `trace` block A/Bing the same job traced vs untraced —
`trace.overhead_pct`, gated by perfgate's `--trace-overhead-max`
at the same <2% budget as every other observability tax.
Sequential single-job
submits per count additionally measure `range_scaling_x` — how much
faster ONE job finishes when the router window-range-shards its
contig across the fleet (a `--contigs 1` workload makes every
multi-replica point range-shard) — gated via `--range-scaling-min`.

RAMP MODE (`--ramp N`): elastic autoscaling under a ramped open-loop
load. One warm replica behind the router, the autoscaler armed with
ceiling N, Poisson arrivals climbing from well inside one replica's
capacity to far outside it, then a slow trickle while the idle fleet
drains back to the floor. The artifact gains an `autoscale` block
(replicas over time, scale up/down counts, `gold_p99_flat` = ramp
p99 over idle p99, `jobs_lost`) which tools/perfgate.py gates via
`autoscale.jobs_lost` == 0 and `autoscale.gold_p99_flat`
(default-when-present; `--ramp-p99-flat-max` makes it mandatory).

AUDIT MODE (`--audit-rate R`): arm the identity-audit sentinel
(racon_tpu/obs/audit.py) on every replica, keep it armed through the
measured warm phases, and A/B the same sequential workload with the
sentinel muted on the same warm server — the wall delta is the real
audit cost. The artifact gains an `audit` block (sampled fraction,
shadow device seconds, mismatch/demotion counts, overhead_pct) which
tools/perfgate.py gates at the <2% observability budget and at ZERO
mismatches (a mismatch on a clean bench workload is a corruption bug,
and also fails the bench directly).

ROUNDS MODE (`--rounds N`): serve-native iterative polishing with the
content-addressed window cache. One warm cache-OFF server runs a
`rounds=N` job (the no-cache per-round walls and the byte-identity
reference), then one warm cache-ON server (serve/wincache.py armed,
optionally with the audit sentinel riding at `--audit-rate`) runs the
SAME job twice — the first submit measures convergence hits (later
rounds re-polish windows whose content already stabilized, so they
skip device dispatch), the second measures the identical-resubmit
ceiling (everything hits). The artifact gains `rounds` (per-round
walls cache-on vs cache-off, `round2_speedup_x` = mean no-cache
round-2+ wall over mean cached round-2+ wall) and `cache`
(`identical` byte-equality cache-on vs cache-off, hit rates, the
cache snapshot) blocks; tools/perfgate.py gates `cache.identical`
whenever the block is present and `rounds.round2_speedup_x` via
`--round2-speedup-min`.

FLOOD MODE (`--flood N`): preemptive-QoS isolation. N free-tenant
submitter threads flood a 2-replica routed fabric in a closed loop
while gold-priority waves measure p99 three ways — idle fabric, flood
with preemption off, flood with preemption on — then a doomed-abort
phase arms the speculative deadline-abort and submits unmeetable
deadlines that must be rejected typed at admission. The artifact gains
a `qos` block (`gold_p99_flat` = gold p99 under flood-with-preemption
over idle, `doomed_abort_saved_s` = EMA-predicted device seconds the
aborts saved) which tools/perfgate.py gates via `qos.gold_p99_flat`
(default-when-present) and `--doomed-abort-min` (mandatory once
requested).

OPEN-LOOP ARRIVAL MODE (`--qps`, optionally a `--qps-curve` sweep):
instead of firing the whole wave at once (closed-loop, back-pressure
hides the queueing), jobs arrive by a Poisson process at the target
rate and the bench reports p50/p95/p99 end-to-end latency,
time-to-first-byte (the first streamed `result_part`), achieved vs
offered throughput per rate, and the SATURATION KNEE — the highest
swept rate the server still absorbs (achieved >= 90% of offered). The
curve rides the `--json` artifact under `openloop` so perfgate can gate
the latency tail round over round. `--baseline PATH` embeds a prior
measurement (e.g. the round-barrier design's curve) and prints the
comparison.

    python tools/servebench.py --jobs 4 [--genome-kb 20] [--json out.json]
    python tools/servebench.py --qps 2 --qps-jobs 8 --qps-curve 0.5,1,2,4
"""

from __future__ import annotations

import argparse
import gzip
import json
import os
import statistics
import subprocess
import sys
import tempfile
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                      "/tmp/racon_tpu_jax_cache")
sys.path = [p for p in sys.path if "axon_site" not in p]
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))


def build_dataset(tmpdir: str, genome_kb: int, coverage: int,
                  read_len: int, seed: int, contigs: int = 1):
    """Synthetic ONT-style workload via synthbench's simulator (same
    error model as the scale bench, so serve numbers are comparable).
    `contigs` > 1 splits the genome budget across independent contigs
    — the shape that exercises per-contig result streaming: the first
    contig's bytes hit the wire while later contigs still polish."""
    import random

    all_reads, all_paf, drafts = [], [], []
    per_contig = max(1, genome_kb // max(1, contigs))
    for c in range(max(1, contigs)):
        rng = random.Random(seed + 1000 * c)
        _, draft, reads, paf = simulate_contig(
            rng, per_contig * 1000, coverage, read_len)
        tag = f"c{c}_" if contigs > 1 else ""
        cname = f"draft{c}" if contigs > 1 else "draft"
        drafts.append((cname, draft))
        for name, read in reads:
            all_reads.append((tag + name, read))
        for line in paf:
            fields = line.split("\t")
            fields[0] = tag + fields[0]
            fields[5] = cname
            all_paf.append("\t".join(fields))
    paths = (os.path.join(tmpdir, "reads.fasta.gz"),
             os.path.join(tmpdir, "ovl.paf.gz"),
             os.path.join(tmpdir, "draft.fasta.gz"))
    with gzip.open(paths[0], "wb", compresslevel=1) as f:
        for name, read in all_reads:
            f.write(b">" + name.encode() + b"\n" + read + b"\n")
    with gzip.open(paths[1], "wb", compresslevel=1) as f:
        f.write(("\n".join(all_paf) + "\n").encode())
    with gzip.open(paths[2], "wb", compresslevel=1) as f:
        for cname, draft in drafts:
            f.write(b">" + cname.encode() + b"\n" + draft + b"\n")
    return paths


def simulate_contig(rng, genome_len, coverage, read_len):
    from synthbench import simulate

    return simulate(rng, genome_len, coverage, read_len, 0.12, 0.10)


def merge_fleet_snaps(snaps: list[dict]) -> dict:
    """Aggregate N replicas' stats snapshots into one artifact view:
    queue/SLO counters SUM (the gated slo.miss_rate must see every
    replica's deadlines, not replica 0's), batcher activity counters
    sum, high-water marks take the max, and lane rows concatenate
    tagged with their replica. Non-additive detail (occupancy,
    latency percentiles, tenants) stays replica 0's."""
    if len(snaps) == 1:
        return snaps[0]
    out = json.loads(json.dumps(snaps[0]))  # deep copy, JSON-shaped
    q, b, slo = out["queue"], out["batcher"], out["slo"]
    q_sum = ("submitted", "admitted", "rejected_full",
             "rejected_draining", "rejected_quota", "expired",
             "completed", "failed", "deadline_hit", "deadline_miss",
             "depth")
    b_sum = ("iterations", "shared_iterations", "solo_iterations",
             "jobs", "windows", "host_s", "compiles", "compile_s")
    for i, lane in enumerate(b.get("lanes") or []):
        lane["replica"] = 0
    for r, s in enumerate(snaps[1:], start=1):
        for k in q_sum:
            if k in s["queue"]:
                q[k] = q.get(k, 0) + s["queue"][k]
        for k in ("deadline_hit", "deadline_miss", "expired"):
            slo[k] += s["slo"][k]
        sb = s["batcher"]
        for k in b_sum:
            if k in sb:
                b[k] = b.get(k, 0) + sb[k]
        for k, v in sb.items():
            if k.startswith("max_"):
                b[k] = max(b.get(k, 0), v)
        b["lanes"] = (b.get("lanes") or []) + [
            dict(lane, replica=r) for lane in (sb.get("lanes") or [])]
        out["inflight"] += s.get("inflight", 0)
    deadlined = slo["deadline_hit"] + slo["deadline_miss"]
    slo["miss_rate"] = (round(slo["deadline_miss"] / deadlined, 4)
                        if deadlined else 0.0)
    return out


def _mesh_block(batcher_snap: dict) -> dict:
    """The shared mesh-block schema (parallel/mesh.py), with the serve
    batcher's actual lane count riding in."""
    from racon_tpu.parallel.mesh import mesh_info

    return mesh_info(
        worker_lanes=batcher_snap.get("worker_lanes", 1))


def spawn_replica(sock: str, args):
    """One REAL `racon_tpu serve` replica subprocess. The fleet benches
    (--router / --ramp) spawn replicas as processes, not in-process
    threads: N PolishServers in one interpreter share a single GIL, so
    thread-replicas can only ever measure overhead, never scaling."""
    env = dict(os.environ)
    env["PYTHONPATH"] = os.pathsep.join(
        [REPO] + [q for q in env.get("PYTHONPATH", "").split(os.pathsep)
                  if q and "axon_site" not in q])
    if getattr(args, "device_latency_ms", 0):
        # the device-dominated posture: every replica pipeline stalls a
        # simulated accelerator round-trip per chunk (off-CPU, so waits
        # overlap across replica processes even on a small host)
        env["RACON_TPU_DEVICE_LATENCY_S"] = str(
            args.device_latency_ms / 1000.0)
    if getattr(args, "device_latency_x", 0):
        env["RACON_TPU_DEVICE_LATENCY_X"] = str(args.device_latency_x)
    if getattr(args, "host_poa_chunk", 0):
        # smaller chunks -> per-chunk latency paces proportionally to a
        # job's window count (a range shard carries fewer windows, so
        # it pays proportionally less simulated device time)
        env["RACON_TPU_HOST_POA_CHUNK"] = str(args.host_poa_chunk)
    return subprocess.Popen(
        [sys.executable, "-m", "racon_tpu.cli", "serve",
         "--socket", sock, "--workers", str(args.workers),
         "--no-warmup", "-t", str(args.threads),
         "-c", str(args.tpupoa_batches),
         "--tpualigner-batches", str(args.tpualigner_batches)],
        env=env, stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)


def wait_replica(PolishClient, sock: str,
                 deadline_s: float = 120.0) -> None:
    probe = PolishClient(socket_path=sock, timeout=10)
    deadline = time.perf_counter() + deadline_s
    while time.perf_counter() < deadline:
        try:
            probe.request({"type": "ping"})
            return
        except Exception:  # noqa: BLE001 — still starting
            time.sleep(0.2)
    raise RuntimeError(f"replica {sock} never came up")


def stop_replica(proc) -> None:
    try:
        proc.terminate()
    except Exception:  # noqa: BLE001 — already gone
        pass
    try:
        proc.wait(timeout=30)
    except Exception:  # noqa: BLE001 — escalate
        try:
            proc.kill()
            proc.wait(timeout=5)
        except Exception:  # noqa: BLE001 — nothing left to do
            pass


def cold_cli_run(paths, args) -> tuple[float, bytes]:
    """One fresh-process CLI run: the full cold tax, wall-clocked."""
    env = {k: v for k, v in os.environ.items() if "axon" not in k.lower()}
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = os.pathsep.join(
        [REPO] + [p for p in env.get("PYTHONPATH", "").split(os.pathsep)
                  if p and "axon_site" not in p])
    cmd = [sys.executable, "-m", "racon_tpu.cli",
           "-t", str(args.threads)]
    if args.tpupoa_batches:
        cmd += ["-c", str(args.tpupoa_batches)]
    if args.tpualigner_batches:
        cmd += ["--tpualigner-batches", str(args.tpualigner_batches)]
    cmd += list(paths)
    t0 = time.perf_counter()
    proc = subprocess.run(cmd, env=env, capture_output=True)
    dt = time.perf_counter() - t0
    if proc.returncode != 0:
        print(proc.stderr.decode(errors="replace"), file=sys.stderr)
        raise SystemExit(f"[servebench] cold CLI run failed "
                         f"(rc {proc.returncode})")
    return dt, proc.stdout


def check_slo(args, PolishClient, PolishServer) -> int:
    """`--check-slo`: one warm server, one concurrent wave with per-job
    deadlines, three gated cells printed as a faultcheck-style row —
    p99 end-to-end latency, deadline-miss rate (from the server's OWN
    SLO accounting, the same numbers admission control uses), and a
    live `scrape` that must return Prometheus text with populated
    latency histograms. Exit 0 only when every cell passes."""
    with tempfile.TemporaryDirectory(prefix="racon_slo_") as tmp:
        print(f"[servebench] SLO gate: {args.jobs} jobs, deadline "
              f"{args.deadline:.0f}s, p99<= {args.slo_p99:.1f}s, "
              f"miss-rate<= {args.slo_miss_rate:.2f}", file=sys.stderr)
        paths = build_dataset(tmp, args.genome_kb, args.coverage,
                              args.read_len, args.seed,
                              contigs=args.contigs)
        sock = os.path.join(tmp, "serve.sock")
        server = PolishServer(
            socket_path=sock, workers=args.workers, warmup=False,
            job_threads=args.threads,
            flight_dir=os.path.join(tmp, "flight"),
            tpu_poa_batches=args.tpupoa_batches,
            tpu_aligner_batches=args.tpualigner_batches)
        server.warmup(paths=paths)
        server.start()
        client = PolishClient(socket_path=sock)

        latencies = [None] * args.jobs

        def submit(i):
            t0 = time.perf_counter()
            try:
                client.submit(*paths, deadline_s=args.deadline,
                              retries=5)
            except Exception as exc:
                print(f"[servebench] job {i} failed: {exc}",
                      file=sys.stderr)
                return
            latencies[i] = time.perf_counter() - t0

        threads = [threading.Thread(target=submit, args=(i,))
                   for i in range(args.jobs)]
        for t in threads:
            t.start()
        # scrape mid-wave: the live-exposition contract is part of the
        # gate (must answer while jobs are executing)
        live = client.scrape()
        for t in threads:
            t.join()
        snap = client.stats()
        server.drain(timeout=30)

    from racon_tpu.serve.queue import nearest_rank

    cells = []
    done = sorted(v for v in latencies if v is not None)
    if len(done) < args.jobs:
        cells.append(("completed", False,
                      f"{len(done)}/{args.jobs} jobs"))
    p99 = nearest_rank(done, 0.99) if done else float("inf")
    cells.append(("p99", p99 <= args.slo_p99,
                  f"{p99:.2f}s <= {args.slo_p99:.1f}s"))
    slo = snap.get("slo") or {}
    miss_rate = float(slo.get("miss_rate", 1.0))
    cells.append(("miss-rate", miss_rate <= args.slo_miss_rate,
                  f"{miss_rate:.2f} <= {args.slo_miss_rate:.2f} "
                  f"({slo.get('deadline_miss', '?')} missed, "
                  f"{slo.get('expired', '?')} expired)"))
    hist_lines = [ln for ln in live.splitlines()
                  if "_bucket{" in ln]
    populated = any(not ln.rstrip().endswith(" 0")
                    for ln in hist_lines)
    cells.append(("scrape", bool(hist_lines) and populated,
                  f"{len(live.splitlines())} lines, "
                  f"{len(hist_lines)} buckets, "
                  f"{'populated' if populated else 'EMPTY'}"))
    row = "  ".join(f"{name} {'pass' if ok else 'FAIL'} ({detail})"
                    for name, ok, detail in cells)
    failures = sum(not ok for _, ok, _ in cells)
    print(f"[servebench] slo  {row}", file=sys.stderr)
    print(f"[servebench] SLO gate "
          f"{'PASS' if not failures else 'FAIL'}: "
          f"{len(cells) - failures}/{len(cells)} cells green",
          file=sys.stderr)
    return 1 if failures else 0


def run_router_bench(args, PolishClient, PolishServer) -> int:
    """`--router N`: job throughput through the shard-aware router
    (racon_tpu/serve/router.py) vs replica count. Starts N warm
    replica SUBPROCESSES once (real processes — in-process
    thread-replicas share one GIL and cannot scale), then for each
    swept count c (1, 2, 4 ...
    capped at N; N always included) fronts the first c replicas with a
    PolishRouter and fires the same concurrent wave through it.
    Reports jobs/s per count, the requeue count (zero on a healthy
    fleet — any requeue here is a real replica loss and fails the
    bench), the router's merge overhead (job wall minus the slowest
    shard's exec seconds: the fan-out + merge + ledger tax) and
    byte-identity vs a direct single-replica submit. Each swept count
    also times SEQUENTIAL single-job submits: with a single-contig
    workload (`--contigs 1`) the router splits the one contig by
    window range across every routable replica, so the per-job wall
    drops as replicas join — `range_scaling_x` (single-job wall at 1
    replica over the wall at N) is that claim, reported whenever the
    top point actually range-sharded. `--json` rides the curve out as
    a `router` artifact block with `scaling_x` (jobs/s at N replicas
    over jobs/s at 1) which tools/perfgate.py gates via
    `router.identical` (always, when the block is present),
    `--router-scaling-min` and `--range-scaling-min` (each mandatory
    once requested). The sequential submits also stream parts, so the
    block carries the routed `ttfb_s` (submit start to the first
    part-routed frame — the router twin of the direct-submit ttfb),
    and the top count A/Bs the same job with the distributed-trace
    plane armed (submit_traced: client + router spans, per-replica
    trace_pull, clock-chained merge) vs untraced into a `trace`
    artifact block whose `overhead_pct` perfgate holds to its <=2%
    budget (`--trace-overhead-max`)."""
    from racon_tpu.serve.queue import nearest_rank
    from racon_tpu.serve.router import PolishRouter

    n_max = max(1, args.router)
    counts = sorted({c for c in (1, 2, 4) if c < n_max} | {n_max})
    fail: list[str] = []
    curve: list[dict] = []
    with tempfile.TemporaryDirectory(prefix="racon_routerbench_") as tmp:
        print(f"[servebench] router bench: {n_max} replica(s), sweep "
              f"{counts}, {args.jobs} jobs per wave", file=sys.stderr)
        paths = build_dataset(tmp, args.genome_kb, args.coverage,
                              args.read_len, args.seed,
                              contigs=args.contigs)
        procs, socks = [], []
        try:
            t0 = time.perf_counter()
            for k in range(n_max):
                sock = os.path.join(tmp, f"rep{k}.sock")
                procs.append(spawn_replica(sock, args))
                socks.append(sock)
            for sock in socks:
                wait_replica(PolishClient, sock)
                # one direct job warms this replica's engines on the
                # bench's own shapes before anything is timed
                PolishClient(socket_path=sock).submit(*paths)
            print(f"[servebench] {n_max} replica subprocess(es) warm "
                  f"in {time.perf_counter() - t0:.2f}s",
                  file=sys.stderr)
            # the identity reference: one direct submit to a single
            # replica — every routed job must reproduce these bytes
            solo = PolishClient(socket_path=socks[0]).submit(*paths)

            for c in counts:
                router = PolishRouter(
                    replicas=socks[:c],
                    socket_path=os.path.join(tmp, f"router{c}.sock"),
                    journal=os.path.join(tmp, f"router{c}.jsonl"))
                router.start()
                results: list = [None] * args.jobs

                def submit(i):
                    try:
                        cl = PolishClient(
                            socket_path=router.config.socket_path)
                        results[i] = cl.submit(*paths, retries=5)
                    except Exception as exc:
                        print(f"[servebench] router job {i} "
                              f"({c} replicas) failed: {exc}",
                              file=sys.stderr)

                threads = [threading.Thread(target=submit, args=(i,))
                           for i in range(args.jobs)]
                t_wave = time.perf_counter()
                for t in threads:
                    t.start()
                for t in threads:
                    t.join()
                wall = time.perf_counter() - t_wave
                # sequential single-JOB latency: the number window-range
                # sharding moves. The wave above measures fleet
                # THROUGHPUT (more replicas, more concurrent jobs);
                # these submits measure how much faster ONE job
                # finishes when the router can split a contig by
                # window range across every routable replica
                seq_cl = PolishClient(
                    socket_path=router.config.socket_path)
                seq_walls: list[float] = []
                ttfbs: list[float] = []
                r_seq = None
                for _ in range(3):
                    t_seq = time.perf_counter()
                    marks: list[float] = []
                    r_seq = seq_cl.submit(
                        *paths, retries=5,
                        on_part=lambda f: marks.append(
                            time.perf_counter()))
                    seq_walls.append(time.perf_counter() - t_seq)
                    # routed time-to-first-part: submit start to the
                    # first result_part frame the router forwarded —
                    # the router twin of the direct-submit ttfb the
                    # latency sweep reports
                    if marks:
                        ttfbs.append(marks[0] - t_seq)
                    if r_seq.fasta != solo.fasta:
                        fail.append(f"router x{c}: sequential job "
                                    "FASTA diverged from the direct "
                                    "single-replica bytes")
                # trace overhead A/B at the top count: the same
                # sequential job with the full distributed-trace
                # plane armed (client spans + router spans + replica
                # trace_pull + merge) vs the untraced walls above —
                # min-of-3 on both sides, the steady-state number
                # perfgate gates as trace.overhead_pct
                trace_pt = None
                if c == n_max:
                    tr_walls: list[float] = []
                    for _ in range(3):
                        t_tr = time.perf_counter()
                        r_tr, _doc = seq_cl.submit_traced(*paths,
                                                          retries=5)
                        tr_walls.append(time.perf_counter() - t_tr)
                        if r_tr.fasta != solo.fasta:
                            fail.append(
                                f"router x{c}: traced job FASTA "
                                "diverged from the direct "
                                "single-replica bytes")
                    base_w = min(seq_walls) if seq_walls else 0.0
                    traced_w = min(tr_walls)
                    trace_pt = {
                        "untraced_wall_s": round(base_w, 3),
                        "traced_wall_s": round(traced_w, 3),
                        "overhead_pct": round(
                            (traced_w - base_w)
                            / max(base_w, 1e-9) * 100.0, 2)}
                requeues = router.counters["requeues"]
                router.drain(timeout=30)
                done = [r for r in results if r is not None]
                identical = bool(done) and all(r.fasta == solo.fasta
                                               for r in done)
                # merge overhead: what the router ADDED on top of the
                # slowest shard — fan-out, part forwarding, contig-order
                # merge and the journal ledger
                ov = [(r.router["wall_s"] - r.router["shard_exec_max_s"])
                      / max(r.router["wall_s"], 1e-9) * 100.0
                      for r in done
                      if r.router.get("wall_s")]
                shards = [r.router.get("shards", 1) for r in done]
                rb = r_seq.router if r_seq is not None else {}
                pt = {"replicas": c, "jobs": args.jobs,
                      "completed": len(done),
                      "wall_s": round(wall, 3),
                      "jobs_per_s": round(len(done) / max(wall, 1e-9),
                                          3),
                      "shards_mean": round(statistics.mean(shards), 2)
                      if shards else 0,
                      "job_wall_s": round(min(seq_walls), 3)
                      if seq_walls else None,
                      "ttfb_s": round(min(ttfbs), 3)
                      if ttfbs else None,
                      "range": bool(rb.get("range")),
                      "range_shards": rb.get("range_shards"),
                      "requeues": requeues,
                      "merge_overhead_pct": round(
                          nearest_rank(sorted(ov), 0.50), 2)
                      if ov else None,
                      "identical": identical}
                curve.append(pt)
                print(f"[servebench] router x{c}: "
                      f"{pt['completed']}/{args.jobs} jobs in "
                      f"{wall:.2f}s ({pt['jobs_per_s']:.3f} jobs/s, "
                      f"{pt['shards_mean']:.1f} shards/job, "
                      f"merge overhead "
                      f"{pt['merge_overhead_pct'] or 0:.2f}%, "
                      f"{requeues} requeues), single job "
                      f"{pt['job_wall_s']:.2f}s"
                      + (f" range-sharded x{pt['range_shards']}"
                         if pt["range"] else "")
                      + f" [{'OK' if identical else 'FAIL'} identity]",
                      file=sys.stderr)
                if len(done) < args.jobs:
                    fail.append(f"router x{c}: only {len(done)}/"
                                f"{args.jobs} jobs completed")
                if not identical:
                    fail.append(f"router x{c}: routed FASTA diverged "
                                "from the direct single-replica bytes")
                if requeues:
                    fail.append(f"router x{c}: {requeues} requeues on "
                                "a healthy fleet (a replica dropped "
                                "mid-shard)")
        finally:
            for proc in procs:
                stop_replica(proc)

    scaling_x = (curve[-1]["jobs_per_s"]
                 / max(curve[0]["jobs_per_s"], 1e-9)) if curve else 0.0
    router_block = {
        "replicas_max": n_max,
        "jobs": args.jobs,
        "curve": curve,
        "jobs_per_s": curve[-1]["jobs_per_s"] if curve else 0.0,
        "job_wall_s": curve[-1]["job_wall_s"] if curve else None,
        "ttfb_s": curve[-1]["ttfb_s"] if curve else None,
        "range": bool(curve) and bool(curve[-1].get("range")),
        "requeues": sum(pt["requeues"] for pt in curve),
        "merge_overhead_pct": max(
            (pt["merge_overhead_pct"] for pt in curve
             if pt["merge_overhead_pct"] is not None), default=None),
        "identical": bool(curve) and all(pt["identical"]
                                         for pt in curve),
        "scaling_x": round(scaling_x, 3),
        "device_latency_ms": args.device_latency_ms,
        "device_latency_x": args.device_latency_x,
        "host_poa_chunk": args.host_poa_chunk,
    }
    print(f"[servebench] router scaling: x{scaling_x:.2f} jobs/s at "
          f"{n_max} replica(s) vs 1 "
          f"({router_block['requeues']} requeues total)",
          file=sys.stderr)
    # single-JOB scaling, reported only when the highest-count point
    # actually range-sharded (a multi-contig workload at few replicas
    # splits whole contigs instead — no sub-contig claim to make there)
    if router_block["range"] and curve[0].get("job_wall_s"):
        router_block["range_shards"] = curve[-1].get("range_shards")
        router_block["range_scaling_x"] = round(
            curve[0]["job_wall_s"]
            / max(curve[-1]["job_wall_s"], 1e-9), 3)
        print(f"[servebench] range scaling: one job "
              f"x{router_block['range_scaling_x']:.2f} faster at "
              f"{n_max} replica(s) vs 1 "
              f"({curve[0]['job_wall_s']:.2f}s -> "
              f"{curve[-1]['job_wall_s']:.2f}s, "
              f"{router_block['range_shards']} window-range shards — "
              "perfgate gates router.range_scaling_x)",
              file=sys.stderr)
    if args.json:
        artifact = {"mode": "router", "jobs": args.jobs,
                    "router": router_block, "pass": not fail}
        if trace_pt is not None:
            artifact["trace"] = trace_pt
            print(f"[servebench] trace overhead: "
                  f"{trace_pt['overhead_pct']:+.2f}% "
                  f"({trace_pt['untraced_wall_s']:.2f}s untraced -> "
                  f"{trace_pt['traced_wall_s']:.2f}s traced — "
                  "perfgate gates trace.overhead_pct)",
                  file=sys.stderr)
        with open(args.json, "w") as fh:
            json.dump(artifact, fh, indent=2, sort_keys=True)
        print(f"[servebench] wrote {args.json}", file=sys.stderr)
    if fail:
        for f in fail:
            print(f"[servebench] FAIL: {f}", file=sys.stderr)
        return 1
    print("[servebench] PASS", file=sys.stderr)
    return 0


def run_rounds_bench(args, PolishClient, PolishServer) -> int:
    """`--rounds N`: iterative serve-native polishing with and without
    the content-addressed window cache. Three submits, two warm
    servers:

      1. cache OFF, `rounds=N`  -> byte-identity reference + the
         no-cache per-round walls;
      2. cache ON,  `rounds=N`  -> convergence hits: rounds whose
         windows stopped changing skip device dispatch;
      3. cache ON,  `rounds=N` again -> the identical-resubmit
         ceiling (every window hits, zero device iterations).

    Gates (exit status): all three FASTAs byte-identical, every submit
    completed all N rounds, the cached run saw a NONZERO hit rate, and
    — when `--audit-rate` armed the sentinel on the cached server —
    zero audit mismatches. The `--json` artifact carries `rounds` /
    `cache` blocks for tools/perfgate.py (`cache.identical`,
    `rounds.round2_speedup_x` via `--round2-speedup-min`)."""
    n = max(1, args.rounds)
    fail: list[str] = []
    with tempfile.TemporaryDirectory(prefix="racon_roundsbench_") as tmp:
        print(f"[servebench] rounds bench: {n} rounds, cache off vs "
              f"on (+ resubmit)", file=sys.stderr)
        paths = build_dataset(tmp, args.genome_kb, args.coverage,
                              args.read_len, args.seed,
                              contigs=args.contigs)
        base_kw = dict(workers=args.workers, warmup=False,
                       job_threads=args.threads,
                       tpu_poa_batches=args.tpupoa_batches,
                       tpu_aligner_batches=args.tpualigner_batches)

        off = PolishServer(socket_path=os.path.join(tmp, "off.sock"),
                           **base_kw)
        off.warmup(paths=paths)
        off.start()
        try:
            r_off = PolishClient(
                socket_path=off.config.socket_path).submit(
                *paths, rounds=n)
        finally:
            off.drain(timeout=30)

        on_kw = dict(base_kw, wincache=True)
        if args.audit_rate is not None:
            on_kw["audit_rate"] = args.audit_rate
        on = PolishServer(socket_path=os.path.join(tmp, "on.sock"),
                          **on_kw)
        on.warmup(paths=paths)
        on.start()
        try:
            client = PolishClient(socket_path=on.config.socket_path)
            r_on = client.submit(*paths, rounds=n)
            r_on2 = client.submit(*paths, rounds=n)
            cache_snap = on.batcher.wincache.snapshot()
            audit_snap = (on.auditor.snapshot()
                          if on.auditor is not None else None)
        finally:
            on.drain(timeout=30)

    identical = (r_on.fasta == r_off.fasta
                 and r_on2.fasta == r_off.fasta)
    if not identical:
        fail.append("cached rounds FASTA diverged from the cache-off "
                    "bytes")
    for tag, r in (("off", r_off), ("on", r_on), ("resubmit", r_on2)):
        if r.rounds.get("completed") != n:
            fail.append(f"{tag} submit completed "
                        f"{r.rounds.get('completed')}/{n} rounds")

    def _walls(res):
        return [p["wall_s"] for p in res.rounds.get("per_round", [])]

    def _rate(res):
        c = res.rounds.get("cache") or {}
        total = c.get("hits", 0) + c.get("misses", 0)
        return round(c.get("hits", 0) / total, 4) if total else 0.0

    off_w, on_w, on2_w = _walls(r_off), _walls(r_on), _walls(r_on2)
    # round-2+ speedup: round 1 always pays full dispatch (and, warmed
    # on the bench's own shapes, may hit warmup-populated entries) —
    # the cache's claim is about LATER rounds, where converged windows
    # repeat verbatim
    off_r2 = statistics.mean(off_w[1:]) if len(off_w) > 1 else None
    on_r2 = statistics.mean(on_w[1:]) if len(on_w) > 1 else None
    speedup = (round(off_r2 / max(on_r2, 1e-9), 3)
               if off_r2 is not None and on_r2 is not None else None)
    resub_x = (round(statistics.mean(off_w)
                     / max(statistics.mean(on2_w), 1e-9), 3)
               if off_w and on2_w else None)
    hit_rate, hit_rate2 = _rate(r_on), _rate(r_on2)
    if hit_rate2 <= 0.0:
        fail.append("cached resubmit saw a zero hit rate — the cache "
                    "never engaged")
    if audit_snap is not None and audit_snap["mismatches"]:
        fail.append(f"audit sentinel caught "
                    f"{audit_snap['mismatches']} mismatches with the "
                    "window cache armed")

    print(f"[servebench] rounds x{n} cache-off walls: "
          + " ".join(f"{w:.2f}" for w in off_w), file=sys.stderr)
    print(f"[servebench] rounds x{n} cache-on  walls: "
          + " ".join(f"{w:.2f}" for w in on_w)
          + f"  (hit rate {hit_rate * 100:.1f}%)", file=sys.stderr)
    print(f"[servebench] rounds x{n} resubmit  walls: "
          + " ".join(f"{w:.2f}" for w in on2_w)
          + f"  (hit rate {hit_rate2 * 100:.1f}%)", file=sys.stderr)
    if speedup is not None:
        print(f"[servebench] round-2+ mean: {off_r2:.3f}s no-cache vs "
              f"{on_r2:.3f}s cached — x{speedup:.2f} "
              f"[{'OK' if speedup > 1.0 else 'FAIL'}]; resubmit "
              f"x{resub_x:.2f}", file=sys.stderr)
    if audit_snap is not None:
        print(f"[servebench] audit over cached rounds: "
              f"{audit_snap['audited']} audited "
              f"({audit_snap['mismatches']} mismatches) "
              f"[{'OK' if not audit_snap['mismatches'] else 'FAIL'}]",
              file=sys.stderr)
    print(f"[servebench] identity cache-on vs cache-off: "
          f"[{'OK' if identical else 'FAIL'}]", file=sys.stderr)

    if args.json:
        rounds_block = {
            "requested": n,
            "completed": r_on.rounds.get("completed"),
            "per_round": r_on.rounds.get("per_round"),
            "per_round_nocache": r_off.rounds.get("per_round"),
            "round2plus_nocache_mean_s": (round(off_r2, 4)
                                          if off_r2 is not None
                                          else None),
            "round2plus_cached_mean_s": (round(on_r2, 4)
                                         if on_r2 is not None
                                         else None),
            "round2_speedup_x": speedup,
        }
        cache_block = {
            "identical": identical,
            "hit_rate": hit_rate,
            "resubmit": {"hit_rate": hit_rate2,
                         "per_round": r_on2.rounds.get("per_round"),
                         "speedup_x": resub_x},
            "snapshot": cache_snap,
        }
        cb = r_on.rounds.get("cache") or {}
        cache_block.update(hits=cb.get("hits"), misses=cb.get("misses"))
        artifact = {"mode": "rounds", "jobs": 3,
                    "rounds": rounds_block, "cache": cache_block,
                    "pass": not fail}
        if audit_snap is not None:
            artifact["audit"] = {"rate": args.audit_rate,
                                 "windows": audit_snap["windows"],
                                 "sampled": audit_snap["sampled"],
                                 "audited": audit_snap["audited"],
                                 "mismatches": audit_snap["mismatches"],
                                 "repaired": audit_snap["repaired"]}
        with open(args.json, "w") as fh:
            json.dump(artifact, fh, indent=2, sort_keys=True)
        print(f"[servebench] wrote {args.json}", file=sys.stderr)

    if fail:
        for f in fail:
            print(f"[servebench] FAIL: {f}", file=sys.stderr)
        return 1
    print("[servebench] PASS", file=sys.stderr)
    return 0


def run_fragment_bench(args, PolishClient, PolishServer) -> int:
    """`--fragment N`: serve-native fragment error correction (the
    read-vs-read mode, `mode: "fragment"` on the wire). One warm
    server, three measurements:

      1. identity: one fragment submit vs a solo kF run on the same
         files — byte-identical, the gate that makes the throughput
         numbers meaningful;
      2. fragment wave: N concurrent fragment jobs, closed loop ->
         jobs/s, latency percentiles, streamed parts per job (the
         server runs with a small `frag_group` so every job really
         streams multiple bounded read groups);
      3. contig wave: the standard contig workload through the SAME
         warm server -> the comparison row. Fragment jobs are
         per-read-pile corrections with no contig assembly, so their
         jobs/s must land ABOVE the contig rate at a flat p99 — that
         ratio is the `fragment.vs_contig_x` column.

    Gates (exit status): byte-identity, every wave job completed, and
    vs_contig_x > 1. The `--json` artifact carries a `fragment` block
    for tools/perfgate.py (`fragment.identical` whenever the block is
    present, `--fragment-jobs-min` as the mandatory absolute floor on
    `fragment.jobs_per_s`)."""
    from racon_tpu.core.polisher import PolisherType, create_polisher
    from racon_tpu.serve.queue import nearest_rank
    from racon_tpu.serve.server import make_fragment_dataset

    n_jobs = max(2, args.fragment)
    fail: list[str] = []
    with tempfile.TemporaryDirectory(prefix="racon_fragbench_") as tmp:
        print(f"[servebench] fragment bench: {n_jobs} fragment jobs "
              "vs the contig workload, one warm server",
              file=sys.stderr)
        frag_dir = os.path.join(tmp, "frag")
        os.makedirs(frag_dir)
        frag_paths = make_fragment_dataset(frag_dir)
        contig_paths = build_dataset(tmp, args.genome_kb,
                                     args.coverage, args.read_len,
                                     args.seed, contigs=args.contigs)

        # the solo oracle: same files, same kF parameters the serve
        # path uses (ServeConfig defaults) — one process, no serving
        solo_p = create_polisher(*frag_paths, PolisherType.kF, 500,
                                 10.0, 0.3, num_threads=args.threads)
        solo_p.initialize()
        solo = b"".join(b">" + s.name.encode() + b"\n" + s.data + b"\n"
                        for s in solo_p.polish(True))
        n_reads = solo.count(b">")

        srv = PolishServer(socket_path=os.path.join(tmp, "serve.sock"),
                           workers=args.workers, warmup=False,
                           job_threads=args.threads,
                           tpu_poa_batches=args.tpupoa_batches,
                           tpu_aligner_batches=args.tpualigner_batches,
                           frag_group=8)
        srv.warmup(paths=contig_paths)
        srv.start()
        try:
            client = PolishClient(socket_path=srv.config.socket_path)

            # ---- identity + streamed decomposition, one warm job each
            parts: list[dict] = []
            r = client.submit(*frag_paths, fragment=True,
                              on_part=parts.append)
            identical = r.fasta == solo
            if not identical:
                fail.append("serve fragment FASTA diverged from the "
                            "solo kF bytes")
            client.submit(*contig_paths)  # warm the contig job path too

            def wave(paths, n, label, **kw):
                lat: list = [None] * n
                nparts = [0] * n

                def submit(i):
                    t0 = time.perf_counter()

                    def on_part(_frame, _i=i):
                        nparts[_i] += 1

                    try:
                        client.submit(*paths, retries=8,
                                      on_part=on_part, **kw)
                    except Exception as exc:
                        print(f"[servebench] {label} job {i} failed: "
                              f"{exc}", file=sys.stderr)
                        return
                    lat[i] = time.perf_counter() - t0

                threads = [threading.Thread(target=submit, args=(i,))
                           for i in range(n)]
                t_start = time.perf_counter()
                for t in threads:
                    t.start()
                for t in threads:
                    t.join()
                duration = time.perf_counter() - t_start
                done = sorted(v for v in lat if v is not None)
                out = {"jobs": n, "completed": len(done),
                       "duration_s": round(duration, 3),
                       "jobs_per_s": round(
                           len(done) / max(duration, 1e-9), 3),
                       "parts_per_job": round(
                           sum(nparts) / max(n, 1), 2)}
                if done:
                    out.update(
                        p50_s=round(nearest_rank(done, 0.50), 4),
                        p95_s=round(nearest_rank(done, 0.95), 4),
                        p99_s=round(nearest_rank(done, 0.99), 4))
                return out

            frag_wave = wave(frag_paths, n_jobs, "fragment",
                             fragment=True)
            contig_wave = wave(contig_paths,
                               max(2, min(n_jobs, args.jobs)),
                               "contig")
        finally:
            srv.drain(timeout=30)

    for label, w in (("fragment", frag_wave), ("contig", contig_wave)):
        if w["completed"] != w["jobs"]:
            fail.append(f"{label} wave completed "
                        f"{w['completed']}/{w['jobs']} jobs")
    vs_contig = round(frag_wave["jobs_per_s"]
                      / max(contig_wave["jobs_per_s"], 1e-9), 3)
    if vs_contig <= 1.0:
        fail.append(f"fragment jobs/s x{vs_contig:.2f} of contig — "
                    "must be above 1 (a per-read-pile correction "
                    "cheaper than contig assembly)")

    print(f"[servebench] fragment identity vs solo kF "
          f"({n_reads} reads): [{'OK' if identical else 'FAIL'}]",
          file=sys.stderr)
    print(f"[servebench] fragment wave: "
          f"{frag_wave['jobs_per_s']:.2f} jobs/s "
          f"(p99 {frag_wave.get('p99_s', 0):.2f}s, "
          f"{frag_wave['parts_per_job']:.1f} parts/job)",
          file=sys.stderr)
    print(f"[servebench] contig wave:   "
          f"{contig_wave['jobs_per_s']:.2f} jobs/s "
          f"(p99 {contig_wave.get('p99_s', 0):.2f}s) — fragment "
          f"x{vs_contig:.2f} [{'OK' if vs_contig > 1.0 else 'FAIL'}] "
          "(perfgate gates fragment.identical / "
          "--fragment-jobs-min)", file=sys.stderr)

    if args.json:
        fragment_block = {
            "identical": identical,
            "reads": n_reads,
            "jobs_per_s": frag_wave["jobs_per_s"],
            "p50_s": frag_wave.get("p50_s"),
            "p99_s": frag_wave.get("p99_s"),
            "parts_per_job": frag_wave["parts_per_job"],
            "vs_contig_x": vs_contig,
            "wave": frag_wave,
            "contig": contig_wave,
        }
        artifact = {"mode": "fragment", "jobs": n_jobs,
                    "fragment": fragment_block, "pass": not fail}
        with open(args.json, "w") as fh:
            json.dump(artifact, fh, indent=2, sort_keys=True)
        print(f"[servebench] wrote {args.json}", file=sys.stderr)

    if fail:
        for f in fail:
            print(f"[servebench] FAIL: {f}", file=sys.stderr)
        return 1
    print("[servebench] PASS", file=sys.stderr)
    return 0


def run_flood_bench(args, PolishClient, PolishServer) -> int:
    """`--flood N`: preemptive-QoS isolation under load. Two warm
    replicas behind the shard-aware router; N free-tenant submitter
    threads flood the fabric in a closed loop while a gold-priority
    wave runs through it. Three gold waves measure three points:

      1. idle fabric            -> gold p99 baseline
      2. flood, preemption OFF  -> gold p99 degraded by head-of-line
                                   free work (reported, not gated)
      3. flood, preemption ON   -> gold p99 must stay FLAT: each gold
                                   shard preempts the free job on its
                                   replica, runs, and the free job
                                   resumes byte-identically

    then a doomed-abort phase arms the speculative deadline-abort
    (`abort_margin` 0) on every replica and submits free jobs with an
    unmeetable deadline: each must come back typed `deadline-doomed`
    at ADMISSION — before any device dispatch — and the sum of their
    EMA-predicted service seconds is the device time the abort saved.
    The `--json` artifact gains a `qos` block (`gold_p99_flat` = gold
    p99 flood-with-preemption over idle, `doomed_abort_saved_s`)
    which tools/perfgate.py gates via `qos.gold_p99_flat`
    (default-when-present) and `--doomed-abort-min` (mandatory once
    requested). Exit status: every gold job byte-identical to a
    direct submit in every phase, preemptions actually fired in
    phase 3, and every unmeetable-deadline job was aborted doomed."""
    from racon_tpu.serve import DeadlineDoomed
    from racon_tpu.serve.queue import nearest_rank
    from racon_tpu.serve.router import PolishRouter

    n_flood = max(1, args.flood)
    n_gold = max(2, args.jobs)
    fail: list[str] = []
    with tempfile.TemporaryDirectory(prefix="racon_floodbench_") as tmp:
        print(f"[servebench] flood bench: {n_flood} free submitter(s) "
              f"vs {n_gold}-job gold waves, 2 replicas", file=sys.stderr)
        paths = build_dataset(tmp, args.genome_kb, args.coverage,
                              args.read_len, args.seed,
                              contigs=args.contigs)
        servers, socks = [], []
        router = None
        try:
            t0 = time.perf_counter()
            for k in range(2):
                sock = os.path.join(tmp, f"flood_rep{k}.sock")
                srv = PolishServer(
                    socket_path=sock, workers=args.workers,
                    warmup=False, job_threads=args.threads,
                    tpu_poa_batches=args.tpupoa_batches,
                    tpu_aligner_batches=args.tpualigner_batches)
                srv.warmup(paths=paths)
                srv.start()
                servers.append(srv)
                socks.append(sock)
            router = PolishRouter(
                replicas=socks,
                socket_path=os.path.join(tmp, "flood_router.sock"),
                journal=os.path.join(tmp, "flood_router.jsonl")).start()
            client = PolishClient(
                socket_path=router.config.socket_path)
            print(f"[servebench] fabric warm in "
                  f"{time.perf_counter() - t0:.2f}s", file=sys.stderr)
            # the identity reference — and the submit that seeds every
            # replica's service-time EMA for the doomed phase
            solo = client.submit(*paths, tenant="gold", priority=10)

            def gold_wave(tag: str) -> float:
                lat: list[float] = []
                for _ in range(n_gold):
                    t = time.perf_counter()
                    r = client.submit(*paths, tenant="gold",
                                      priority=10, retries=8)
                    lat.append(time.perf_counter() - t)
                    if r.fasta != solo.fasta:
                        fail.append(f"{tag}: gold FASTA diverged from "
                                    "the direct submit bytes")
                return nearest_rank(sorted(lat), 0.99)

            def flood_phase(tag: str, preempt: bool) -> tuple[float,
                                                              int]:
                for srv in servers:
                    srv.config.preempt = preempt
                stop = threading.Event()
                flood_done = [0] * n_flood
                flood_bad: list[str] = []

                def flood(slot: int):
                    mine = PolishClient(
                        socket_path=router.config.socket_path)
                    while not stop.is_set():
                        try:
                            r = mine.submit(*paths, tenant="free",
                                            priority=0, retries=8)
                        except Exception as exc:  # noqa: BLE001
                            flood_bad.append(
                                f"{type(exc).__name__}: {exc}")
                            return
                        if r.fasta != solo.fasta:
                            flood_bad.append("free FASTA diverged")
                            return
                        flood_done[slot] += 1

                threads = [threading.Thread(target=flood, args=(i,))
                           for i in range(n_flood)]
                for t in threads:
                    t.start()
                time.sleep(1.0)  # the flood owns the fabric first
                p99 = gold_wave(tag)
                stop.set()
                for t in threads:
                    t.join(timeout=180)
                for srv in servers:
                    srv.config.preempt = False
                if flood_bad:
                    fail.append(f"{tag}: flood submitter died "
                                f"({flood_bad[0]})")
                print(f"[servebench] {tag}: gold p99 {p99:.2f}s "
                      f"({sum(flood_done)} free jobs completed "
                      "under the wave)", file=sys.stderr)
                return p99, sum(flood_done)

            p99_idle = gold_wave("flood idle-baseline")
            print(f"[servebench] flood idle-baseline: gold p99 "
                  f"{p99_idle:.2f}s", file=sys.stderr)
            p99_nopre, _ = flood_phase("flood preempt-off", False)
            pre0 = sum(s.qos["preemptions"] for s in servers)
            p99_pre, free_done = flood_phase("flood preempt-on", True)
            preemptions = sum(s.qos["preemptions"]
                              for s in servers) - pre0
            if preemptions < 1:
                fail.append("preempt-on flood phase fired zero "
                            "preemptions — gold never displaced free")

            # doomed-abort phase: arm admission-time speculative abort
            # on every replica (margin 0) and submit free jobs whose
            # deadline the populated EMA says is unmeetable — the
            # typed reject must arrive BEFORE any device dispatch
            for srv in servers:
                srv.queue.abort_margin = 0.0
            doomed_n, doomed_saved = 0, 0.0
            try:
                for _ in range(n_gold):
                    try:
                        client.submit(*paths, tenant="free",
                                      deadline_s=0.05)
                        fail.append("unmeetable-deadline job was NOT "
                                    "aborted doomed (it ran to "
                                    "completion)")
                    except DeadlineDoomed as exc:
                        doomed_n += 1
                        doomed_saved += max(exc.predicted_s, 0.0)
            finally:
                for srv in servers:
                    srv.queue.abort_margin = None
            aborted = sum(s.qos["aborted_doomed"] for s in servers)
            print(f"[servebench] doomed-abort: {doomed_n}/{n_gold} "
                  f"unmeetable jobs aborted at admission, "
                  f"~{doomed_saved:.2f} predicted device-seconds "
                  f"saved ({aborted} replica-side aborts)",
                  file=sys.stderr)
        finally:
            if router is not None:
                router.drain(timeout=30)
            for srv in servers:
                srv.drain(timeout=30)

    flat = round(p99_pre / max(p99_idle, 1e-9), 3)
    nopre_x = round(p99_nopre / max(p99_idle, 1e-9), 3)
    qos_block = {
        "replicas": 2,
        "flood_submitters": n_flood,
        "gold_jobs": n_gold,
        "free_jobs_completed": free_done,
        "gold_p99_idle_s": round(p99_idle, 3),
        "gold_p99_flood_nopreempt_s": round(p99_nopre, 3),
        "gold_p99_flood_preempt_s": round(p99_pre, 3),
        "gold_p99_flat": flat,
        "gold_p99_nopreempt_x": nopre_x,
        "preemptions": preemptions,
        "doomed_submitted": n_gold,
        "doomed_aborted": doomed_n,
        "doomed_abort_saved_s": round(doomed_saved, 3),
    }
    print(f"[servebench] gold p99: idle {p99_idle:.2f}s, flood "
          f"no-preempt {p99_nopre:.2f}s (x{nopre_x:.2f}), flood "
          f"preempt {p99_pre:.2f}s (x{flat:.2f} — "
          "perfgate gates qos.gold_p99_flat)", file=sys.stderr)
    if args.json:
        artifact = {"mode": "flood", "jobs": n_gold,
                    "qos": qos_block, "pass": not fail}
        with open(args.json, "w") as fh:
            json.dump(artifact, fh, indent=2, sort_keys=True)
        print(f"[servebench] wrote {args.json}", file=sys.stderr)
    if fail:
        for f in fail:
            print(f"[servebench] FAIL: {f}", file=sys.stderr)
        return 1
    print("[servebench] PASS", file=sys.stderr)
    return 0


def run_ramp_bench(args, PolishClient, PolishServer) -> int:
    """`--ramp N`: elastic autoscaling under a ramped open-loop load.
    The fabric starts at ONE warm replica behind the router with the
    autoscaler (serve/autoscale.py) armed, ceiling N. Poisson arrivals
    ramp the offered rate linearly from 1x to 10x over the wave — the
    1x base rate sits well inside one replica's capacity (measured, or
    `--ramp-qps0`), the 10x peak far outside it, so the loop MUST
    scale up to hold latency. Every job's FASTA must equal a direct
    submit's bytes (with a single-contig workload the scaled-up points
    exercise window-range sharding on every job).

    After the ramp a slow trickle keeps jobs arriving while the idle
    fleet scales back down to the 1-replica floor: a job lost in that
    phase is the scale-down race the unroute-then-drain handshake
    exists to prevent. The bench FAILS on any lost job, any byte
    divergence, a ramp that never scaled up, or a fleet that did not
    drain back to the floor. `--json` writes a `"mode": "ramp"`
    artifact whose `autoscale` block (replicas over time, scale
    up/down counts, gold p99 idle vs ramp as `gold_p99_flat`,
    `jobs_lost`) tools/perfgate.py gates via `autoscale.jobs_lost`
    == 0 (always, when the block is present) and
    `autoscale.gold_p99_flat` (default 2.0; `--ramp-p99-flat-max`
    makes it mandatory)."""
    import random

    from racon_tpu.serve.autoscale import AutoscaleConfig, Autoscaler
    from racon_tpu.serve.queue import nearest_rank
    from racon_tpu.serve.router import PolishRouter

    n_max = max(2, args.ramp)
    n_jobs = max(8, args.ramp_jobs)
    fail: list[str] = []
    samples: list[dict] = []
    with tempfile.TemporaryDirectory(prefix="racon_rampbench_") as tmp:
        print(f"[servebench] ramp bench: 1->{n_max} replicas, "
              f"{n_jobs} Poisson jobs ramping 1x->10x", file=sys.stderr)
        paths = build_dataset(tmp, args.genome_kb, args.coverage,
                              args.read_len, args.seed,
                              contigs=args.contigs)
        # one warm base replica + warm SPARES on the exact spec sockets
        # the autoscaler will ask for (autoscale_1.sock, ...) — all
        # real subprocesses (one GIL per replica), so a scale-up adds
        # genuine capacity and its latency is the healthz handshake,
        # not an interpreter start or a compile
        t0 = time.perf_counter()
        base_sock = os.path.join(tmp, "ramp_base.sock")
        base = spawn_replica(base_sock, args)
        pool: dict = {}
        for i in range(1, n_max):
            spec = os.path.join(tmp, f"autoscale_{i}.sock")
            pool[spec] = spawn_replica(spec, args)
        for sock in [base_sock, *pool]:
            wait_replica(PolishClient, sock)
            PolishClient(socket_path=sock).submit(*paths)  # warm it
        print(f"[servebench] base + {len(pool)} warm spare "
              f"subprocess(es) in {time.perf_counter() - t0:.2f}s",
              file=sys.stderr)
        router = PolishRouter(
            replicas=base_sock,
            socket_path=os.path.join(tmp, "ramp_router.sock"),
            journal=os.path.join(tmp, "ramp_router.jsonl"),
            # under ramped CONCURRENT load, unbounded range fan-out
            # couples every job to every replica (one busy replica
            # gates all merges); two shards per job keeps the
            # sub-contig speedup while the fleet spreads whole jobs
            max_shards=2,
            health_interval_s=0.25).start()
        live: dict = {}

        def spawn(spec):
            proc = pool.pop(spec, None)
            if proc is None:  # past the prebuilt pool: cold spawn
                proc = spawn_replica(spec, args)
            live[spec] = proc
            return spec

        def stop(handle):
            proc = live.pop(handle, None)
            if proc is not None:
                stop_replica(proc)

        scaler = None
        try:
            client = PolishClient(
                socket_path=router.config.socket_path)
            # identity reference; also seeds the service-time EMA
            solo = client.submit(*paths, tenant="gold")
            # idle gold baseline on the 1-replica floor
            idle: list[float] = []
            for _ in range(3):
                t = time.perf_counter()
                r = client.submit(*paths, tenant="gold")
                idle.append(time.perf_counter() - t)
                if r.fasta != solo.fasta:
                    fail.append("idle-baseline FASTA diverged")
            p99_idle = nearest_rank(sorted(idle), 0.99)
            qps0 = args.ramp_qps0 or \
                0.35 / max(statistics.mean(idle), 1e-9)
            print(f"[servebench] idle gold p99 {p99_idle:.2f}s; "
                  f"offered rate {qps0:.2f} -> {qps0 * 10:.2f} jobs/s",
                  file=sys.stderr)

            scaler = Autoscaler(
                router,
                config=AutoscaleConfig(
                    min_replicas=1, max_replicas=n_max,
                    # latency-biased posture: any sustained backlog
                    # beyond one job per replica scales up (the warm
                    # spare pool makes an up cheap); idle still drains
                    # fast enough to exercise scale-down under the
                    # live trickle below
                    interval_s=0.2, up_pressure=1.1, up_sustain_s=0.3,
                    down_idle_s=2.0, cooldown_s=1.0, socket_dir=tmp,
                    ready_timeout_s=30.0,
                    # hold_s > job wall: a burst arrival holds for the
                    # replica its own pressure spawns instead of
                    # serializing behind a committed sibling
                    hold_s=10.0),
                spawn=spawn, stop=stop).start()

            # replicas-over-time sampler: the artifact's scaling trace
            stop_sampling = threading.Event()
            t_wave0 = time.perf_counter()

            def sample():
                while not stop_sampling.is_set():
                    snap = scaler.snapshot()
                    samples.append(
                        {"t_s": round(time.perf_counter() - t_wave0, 2),
                         "replicas": 1 + snap["spawned"],
                         "pressure": round(snap["pressure"], 2)})
                    stop_sampling.wait(0.25)

            sampler = threading.Thread(target=sample, daemon=True)
            sampler.start()

            # the ramp wave: Poisson arrivals, rate climbing 1x -> 10x
            rng = random.Random(args.seed)
            lat: list = [None] * n_jobs
            lost: list[str] = []

            arrive: list = [None] * n_jobs
            shards: list = [None] * n_jobs

            def submit(i):
                t = time.perf_counter()
                arrive[i] = t - t_wave0
                try:
                    r = PolishClient(
                        socket_path=router.config.socket_path).submit(
                            *paths, tenant="gold", retries=8)
                except Exception as exc:  # noqa: BLE001
                    lost.append(f"ramp job {i}: "
                                f"{type(exc).__name__}: {exc}")
                    return
                lat[i] = time.perf_counter() - t
                rb = r.router or {}
                shards[i] = rb.get("shards")
                if r.fasta != solo.fasta:
                    fail.append(f"ramp job {i} FASTA diverged")

            threads = []
            for i in range(n_jobs):
                rate = qps0 * (1.0 + 9.0 * i / max(n_jobs - 1, 1))
                time.sleep(rng.expovariate(rate))
                th = threading.Thread(target=submit, args=(i,))
                th.start()
                threads.append(th)
            for th in threads:
                th.join()
            ramp_done = sorted(v for v in lat if v is not None)
            p99_ramp = (nearest_rank(ramp_done, 0.99) if ramp_done
                        else float("inf"))
            ups = scaler.snapshot()["scale_ups"]
            peak = max((s["replicas"] for s in samples), default=1)
            print(f"[servebench] ramp: {len(ramp_done)}/{n_jobs} jobs, "
                  f"gold p99 {p99_ramp:.2f}s, {ups} scale-up(s), "
                  f"peak {peak} replicas", file=sys.stderr)

            # scale-down under a live trickle: jobs keep arriving
            # slowly while the idle fleet drains back to the floor
            trickle_n = n_max + 1
            for i in range(trickle_n):
                time.sleep(3.0)
                try:
                    r = client.submit(*paths, tenant="gold", retries=8)
                    if r.fasta != solo.fasta:
                        fail.append(f"trickle job {i} FASTA diverged")
                except Exception as exc:  # noqa: BLE001
                    lost.append(f"trickle job {i}: "
                                f"{type(exc).__name__}: {exc}")
            deadline = time.monotonic() + 30.0
            while time.monotonic() < deadline and scaler.spawned:
                time.sleep(0.25)
            snap = scaler.snapshot()
            drained = snap["spawned"] == 0
            stop_sampling.set()
            sampler.join(timeout=5)
        finally:
            if scaler is not None:
                scaler.close()
            router.drain(timeout=30)
            stop_replica(base)
            for proc in [*live.values(), *pool.values()]:
                stop_replica(proc)

    jobs_lost = len(lost)
    for msg in lost:
        fail.append(f"job lost: {msg}")
    if snap["scale_ups"] < 1:
        fail.append("the ramp never scaled up — the offered load "
                    "stayed inside one replica (raise --ramp-jobs or "
                    "lower --ramp-qps0)")
    if snap["scale_downs"] < 1 or not drained:
        fail.append(f"the fleet did not drain back to the floor "
                    f"({snap['spawned']} spawned replica(s) left, "
                    f"{snap['scale_downs']} scale-down(s))")
    flat = round(p99_ramp / max(p99_idle, 1e-9), 3)
    autoscale_block = {
        "replicas_min": 1,
        "replicas_max": n_max,
        "jobs": n_jobs,
        "completed": len(ramp_done),
        "jobs_lost": jobs_lost,
        "qps0": round(qps0, 3),
        "qps_peak": round(qps0 * 10.0, 3),
        "scale_ups": snap["scale_ups"],
        "scale_downs": snap["scale_downs"],
        "spawn_failures": snap["spawn_failures"],
        "drained_to_min": drained,
        "trickle_jobs": trickle_n,
        "gold_p99_idle_s": round(p99_idle, 3),
        "gold_p99_ramp_s": round(p99_ramp, 3),
        "gold_p99_flat": flat,
        "replicas_over_time": samples,
        # the per-job trace behind the p99: arrival offset into the
        # wave, end-to-end latency, shards the router planned
        "ramp_jobs": [
            {"i": i,
             "arrive_s": round(arrive[i], 2) if arrive[i] else None,
             "lat_s": round(lat[i], 2) if lat[i] else None,
             "shards": shards[i]}
            for i in range(n_jobs)],
        "device_latency_ms": args.device_latency_ms,
        "device_latency_x": args.device_latency_x,
        "host_poa_chunk": args.host_poa_chunk,
    }
    print(f"[servebench] autoscale: {snap['scale_ups']} up / "
          f"{snap['scale_downs']} down, {jobs_lost} jobs lost, gold "
          f"p99 idle {p99_idle:.2f}s vs ramp {p99_ramp:.2f}s "
          f"(x{flat:.2f} — perfgate gates autoscale.gold_p99_flat)",
          file=sys.stderr)
    if args.json:
        artifact = {"mode": "ramp", "jobs": n_jobs,
                    "autoscale": autoscale_block, "pass": not fail}
        with open(args.json, "w") as fh:
            json.dump(artifact, fh, indent=2, sort_keys=True)
        print(f"[servebench] wrote {args.json}", file=sys.stderr)
    if fail:
        for f in fail:
            print(f"[servebench] FAIL: {f}", file=sys.stderr)
        return 1
    print("[servebench] PASS", file=sys.stderr)
    return 0


def run_openloop(client, paths, qps: float, n_jobs: int,
                 seed: int) -> dict:
    """One open-loop wave: Poisson arrivals at `qps`, every job
    streaming (progress + result parts), latency percentiles +
    time-to-first-byte + achieved throughput."""
    import random

    from racon_tpu.serve.queue import nearest_rank

    rng = random.Random(seed)
    lat: list = [None] * n_jobs
    ttfb: list = [None] * n_jobs
    threads = []

    def submit(i):
        t0 = time.perf_counter()

        def on_part(frame, _i=i, _t=t0):
            if ttfb[_i] is None:
                ttfb[_i] = time.perf_counter() - _t

        try:
            client.submit(*paths, retries=8, on_part=on_part)
        except Exception as exc:
            print(f"[servebench] openloop job {i} failed: {exc}",
                  file=sys.stderr)
            # keep lat and ttfb over the SAME population: a job that
            # streamed a part but then failed must not skew ttfb low
            ttfb[i] = None
            return
        lat[i] = time.perf_counter() - t0

    t_start = time.perf_counter()
    for i in range(n_jobs):
        time.sleep(rng.expovariate(qps))
        t = threading.Thread(target=submit, args=(i,))
        t.start()
        threads.append(t)
    for t in threads:
        t.join()
    duration = time.perf_counter() - t_start
    done = sorted(v for v in lat if v is not None)
    tb = sorted(v for v in ttfb if v is not None)
    out = {"qps": qps, "jobs": n_jobs, "completed": len(done),
           "duration_s": round(duration, 3),
           "achieved_qps": round(len(done) / max(duration, 1e-9), 3)}
    if done:
        out.update(p50_s=round(nearest_rank(done, 0.50), 4),
                   p95_s=round(nearest_rank(done, 0.95), 4),
                   p99_s=round(nearest_rank(done, 0.99), 4))
    if tb:
        out["ttfb_p50_s"] = round(nearest_rank(tb, 0.50), 4)
    return out


def saturation_knee(curve: list[dict]) -> float | None:
    """The highest swept rate the server still absorbs: achieved
    throughput >= 90% of offered, STOPPING at the first rate that
    fails — a noisy high-rate point that spuriously passes must not
    report capacity above a rate the server demonstrably dropped.
    None when even the lowest rate saturates the server."""
    knee = None
    for pt in sorted(curve, key=lambda p: p["qps"]):
        if pt["achieved_qps"] < 0.9 * pt["qps"]:
            break
        knee = pt["qps"]
    return knee


def _baseline_view(doc: dict) -> dict:
    """Comparable numbers out of a --baseline artifact: either another
    servebench artifact (openloop.curve / warm keys) or a raw curve
    dump ({"curve": [...]})."""
    curve = (doc.get("openloop") or {}).get("curve") or \
        doc.get("curve") or []
    out = {"design": doc.get("design") or doc.get("mode"),
           "curve": curve}
    if curve:
        worst = max((p for p in curve if p.get("p99_s")),
                    key=lambda p: p["qps"], default=None)
        if worst:
            out["p99_s"] = worst.get("p99_s")
            out["ttfb_p50_s"] = worst.get("ttfb_p50_s")
    warm = doc.get("warm") or {}
    out.setdefault("p99_s", warm.get("p99_s"))
    out.setdefault("ttfb_p50_s", warm.get("ttfb_p50_s"))
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--jobs", type=int, default=4,
                    help="concurrent warm submissions")
    ap.add_argument("--cold-runs", type=int, default=None,
                    help="sequential cold CLI runs to time "
                         "(default min(jobs, 3))")
    ap.add_argument("--genome-kb", type=int, default=20)
    ap.add_argument("--contigs", type=int, default=4,
                    help="split the genome budget across this many "
                         "independent contigs (default 4) — "
                         "time-to-first-byte then measures the FIRST "
                         "contig streaming out, the shape the "
                         "continuous batcher optimizes")
    ap.add_argument("--coverage", type=int, default=20)
    ap.add_argument("--read-len", type=int, default=4000)
    ap.add_argument("--seed", type=int, default=42)
    ap.add_argument("-t", "--threads", type=int, default=2)
    ap.add_argument("-c", "--tpupoa-batches", type=int, default=0)
    ap.add_argument("--tpualigner-batches", type=int, default=0)
    ap.add_argument("--workers", type=int, default=2)
    ap.add_argument("--iteration-windows", type=int, default=None,
                    help="continuous feeder iteration bound passed to "
                         "the server (smaller = finer streaming "
                         "granularity and faster late-join turnaround)")
    ap.add_argument("--worker-lanes", type=int, default=None,
                    help="sub-mesh worker lanes passed to the server "
                         "(RACON_TPU_WORKER_LANES): device iterations "
                         "run concurrently across the lane partition; "
                         "with > 1 the bench additionally gates that "
                         "iterations really overlapped on distinct "
                         "lanes (batcher max_concurrent_iterations "
                         ">= 2)")
    ap.add_argument("--audit-rate", type=float, default=None,
                    help="arm the identity-audit sentinel at this "
                         "sampled fraction (RACON_TPU_AUDIT_RATE "
                         "semantics) and measure its overhead: the "
                         "bench runs an extra audit-OFF sequential "
                         "pass on the same warm server and reports the "
                         "wall delta plus the sentinel's sampled "
                         "fraction and shadow device seconds in an "
                         "`audit` artifact block, which "
                         "tools/perfgate.py gates at the <2% "
                         "observability budget (and at zero "
                         "mismatches)")
    ap.add_argument("--json", default=None,
                    help="write the bench-style JSON artifact here")
    ap.add_argument("--fleet", type=int, default=None,
                    help="fleet mode: run this many in-process server "
                         "replicas, round-robin the warm submissions "
                         "across them, and poll the fleet aggregator "
                         "(obs/fleet.py) mid-wave — the artifact gains "
                         "a `fleet` block with aggregator-lag and "
                         "scrape-overhead columns that "
                         "tools/perfgate.py gates at the <2% budget")
    ap.add_argument("--router", type=int, default=None,
                    help="router bench mode: start this many warm "
                         "replicas behind the shard-aware router "
                         "(serve/router.py) and sweep job throughput "
                         "at 1, 2, 4 ... replicas (capped here) — the "
                         "artifact gains a `router` block (jobs/s per "
                         "count, requeue count, merge overhead, "
                         "byte-identity vs a direct submit, scaling_x) "
                         "that tools/perfgate.py gates via "
                         "router.identical and --router-scaling-min")
    ap.add_argument("--rounds", type=int, default=None,
                    help="rounds bench mode: run a rounds=N iterative "
                         "polish on a cache-off and a cache-on warm "
                         "server (plus an identical resubmit) and "
                         "report per-round walls, cache hit rates and "
                         "the round-2+ speedup — the artifact gains "
                         "`rounds` / `cache` blocks that "
                         "tools/perfgate.py gates via cache.identical "
                         "and --round2-speedup-min")
    ap.add_argument("--fragment", type=int, default=None,
                    help="fragment bench mode: run this many "
                         "concurrent serve-native fragment-correction "
                         "jobs (mode: fragment — corrected reads out, "
                         "no contig assembly) on one warm server, "
                         "gated byte-identical to a solo kF run, plus "
                         "a contig comparison wave — the artifact "
                         "gains a `fragment` block (jobs_per_s, p99, "
                         "parts_per_job, vs_contig_x, identical) that "
                         "tools/perfgate.py gates via "
                         "fragment.identical and --fragment-jobs-min")
    ap.add_argument("--flood", type=int, default=None,
                    help="flood bench mode: this many free-tenant "
                         "submitter threads flood a 2-replica routed "
                         "fabric while gold-priority waves measure "
                         "p99 isolation (idle, flood preempt-off, "
                         "flood preempt-on), plus a doomed-abort "
                         "phase — the artifact gains a `qos` block "
                         "(gold_p99_flat, doomed_abort_saved_s) that "
                         "tools/perfgate.py gates via qos.gold_p99_flat "
                         "and --doomed-abort-min")
    ap.add_argument("--ramp", type=int, default=None,
                    help="ramp bench mode: Poisson offered load "
                         "ramping 1x->10x through a routed fabric "
                         "that starts at ONE replica with the elastic "
                         "autoscaler (serve/autoscale.py) armed, "
                         "ceiling at this many replicas — the "
                         "artifact gains an `autoscale` block "
                         "(replicas over time, scale up/down counts, "
                         "gold p99 idle vs ramp, jobs_lost) that "
                         "tools/perfgate.py gates via "
                         "autoscale.jobs_lost == 0 and "
                         "autoscale.gold_p99_flat")
    ap.add_argument("--ramp-jobs", type=int, default=24,
                    help="ramp mode: jobs across the ramp (default 24)")
    ap.add_argument("--device-latency-ms", type=float, default=0.0,
                    help="fleet modes (--router / --ramp): arm "
                         "RACON_TPU_DEVICE_LATENCY_S in every replica "
                         "subprocess — a simulated per-chunk accelerator "
                         "round-trip of this many ms, slept off-CPU, so "
                         "the bench measures device-dominated scaling "
                         "(the production posture) instead of being "
                         "bound by this host's core count; recorded in "
                         "the artifact as device_latency_ms")
    ap.add_argument("--device-latency-x", type=float, default=0.0,
                    help="fleet modes: arm RACON_TPU_DEVICE_LATENCY_X "
                         "in every replica subprocess — each pipeline "
                         "chunk's dispatch is followed by an off-CPU "
                         "sleep of this many times its measured "
                         "duration (a simulated device whose round-trip "
                         "scales with batch size); recorded in the "
                         "artifact as device_latency_x")
    ap.add_argument("--host-poa-chunk", type=int, default=0,
                    help="fleet modes: arm RACON_TPU_HOST_POA_CHUNK in "
                         "every replica subprocess — windows per host "
                         "POA batch call (default 4096), shrunk so "
                         "--device-latency-ms paces proportionally to "
                         "each job's window count")
    ap.add_argument("--ramp-qps0", type=float, default=None,
                    help="ramp mode: the 1x starting arrival rate in "
                         "jobs/s (default: 0.35x the measured "
                         "single-replica capacity)")
    ap.add_argument("--fleet-poll-s", type=float, default=0.25,
                    help="fleet mode: aggregator poll interval during "
                         "the wave (default 0.25s)")
    ap.add_argument("--qps", type=float, default=None,
                    help="open-loop arrival mode: Poisson arrivals at "
                         "this rate (jobs/s) instead of an all-at-once "
                         "wave; reports latency percentiles, "
                         "time-to-first-byte and achieved throughput")
    ap.add_argument("--qps-jobs", type=int, default=8,
                    help="jobs per open-loop wave (default 8)")
    ap.add_argument("--qps-curve", default=None,
                    help="comma-separated extra rates to sweep (e.g. "
                         "'0.5,1,2,4') — the saturation-knee curve in "
                         "the artifact")
    ap.add_argument("--baseline", default=None,
                    help="embed a prior measurement (servebench "
                         "artifact or raw curve JSON) in the artifact "
                         "and print the p99/ttfb comparison")
    ap.add_argument("--check-slo", action="store_true",
                    help="SLO gate mode: run a small concurrent wave "
                         "with per-job deadlines and assert p99 latency "
                         "/ deadline-miss-rate / scrape validity "
                         "(faultcheck-style pass/fail row, exit status "
                         "is the gate)")
    ap.add_argument("--slo-p99", type=float, default=60.0,
                    help="--check-slo: p99 end-to-end latency bound in "
                         "seconds (default 60)")
    ap.add_argument("--slo-miss-rate", type=float, default=0.0,
                    help="--check-slo: allowed deadline-miss rate "
                         "(default 0 — no misses)")
    ap.add_argument("--deadline", type=float, default=120.0,
                    help="--check-slo: per-job deadline_s attached to "
                         "every wave job (default 120)")
    args = ap.parse_args(argv)

    if args.worker_lanes is not None and args.worker_lanes > 1:
        # worker lanes partition the DEVICE LIST: on the CPU bench
        # backend expose enough virtual devices for a real partition
        # (must be set before jax initializes — the same trick the
        # test conftest and synthbench --scale-curve use)
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8"
            ).strip()

    from racon_tpu.serve import PolishClient, PolishServer

    if args.check_slo:
        return check_slo(args, PolishClient, PolishServer)

    if args.router is not None:
        return run_router_bench(args, PolishClient, PolishServer)

    if args.rounds is not None:
        return run_rounds_bench(args, PolishClient, PolishServer)

    if args.fragment is not None:
        return run_fragment_bench(args, PolishClient, PolishServer)

    if args.flood is not None:
        return run_flood_bench(args, PolishClient, PolishServer)

    if args.ramp is not None:
        return run_ramp_bench(args, PolishClient, PolishServer)

    cold_n = args.cold_runs if args.cold_runs is not None \
        else min(args.jobs, 3)

    with tempfile.TemporaryDirectory(prefix="racon_servebench_") as tmp:
        print(f"[servebench] simulating {args.genome_kb} kb at "
              f"{args.coverage}x ...", file=sys.stderr)
        paths = build_dataset(tmp, args.genome_kb, args.coverage,
                              args.read_len, args.seed,
                              contigs=args.contigs)

        # ---- cold: N sequential fresh-process CLI runs
        cold_s: list[float] = []
        cold_out = None
        for i in range(cold_n):
            dt, out = cold_cli_run(paths, args)
            cold_s.append(dt)
            cold_out = out
            print(f"[servebench] cold run {i + 1}/{cold_n}: {dt:.2f}s",
                  file=sys.stderr)

        # ---- warm: one server, N concurrent submissions. The event
        # journal rides the measured run (its <2% overhead is part of
        # the warm numbers, not hidden from them) and is consistency-
        # checked after drain as part of the gate
        n_replicas = max(1, args.fleet or 1)
        server_kw = {}
        if args.iteration_windows is not None:
            server_kw["iteration_windows"] = args.iteration_windows
        if args.worker_lanes is not None:
            server_kw["worker_lanes"] = args.worker_lanes
        if args.audit_rate is not None:
            server_kw["audit_rate"] = args.audit_rate
        servers, clients, journal_paths = [], [], []
        t0 = time.perf_counter()
        for k in range(n_replicas):
            sock = os.path.join(tmp, f"serve{k}.sock")
            journal_path = os.path.join(tmp, f"journal{k}.jsonl")
            journal_paths.append(journal_path)
            srv = PolishServer(
                socket_path=sock, workers=args.workers, warmup=False,
                job_threads=args.threads, journal=journal_path,
                tpu_poa_batches=args.tpupoa_batches,
                tpu_aligner_batches=args.tpualigner_batches,
                **server_kw)
            srv.warmup(paths=paths)  # warm on the SAME shapes jobs use
            srv.start()
            servers.append(srv)
            clients.append(PolishClient(socket_path=sock))
        server, client = servers[0], clients[0]
        warm_ready_s = time.perf_counter() - t0
        print(f"[servebench] {n_replicas} server(s) warm in "
              f"{warm_ready_s:.2f}s "
              f"({server._warm['compiles']} compiles "
              f"{server._warm['compile_s']:.2f}s)", file=sys.stderr)

        # ---- warm sequential: like-for-like vs the cold runs (with
        # --audit-rate the sentinel is armed here — its overhead is part
        # of the measured warm numbers, not hidden from them)
        seq_s: list[float] = []
        seq_results: list = []
        for i in range(cold_n):
            t0 = time.perf_counter()
            seq_results.append(client.submit(*paths))
            seq_s.append(time.perf_counter() - t0)
            print(f"[servebench] warm seq run {i + 1}/{cold_n}: "
                  f"{seq_s[-1]:.2f}s", file=sys.stderr)

        # ---- audit overhead A/B (--audit-rate): the same sequential
        # workload on the same warm server with the sentinel armed vs
        # muted, INTERLEAVED (on, off, on, off, ...) so drift in the
        # host's background load cancels instead of biasing one arm —
        # the wall delta IS the audit cost (sampling + shadow
        # re-execution + compare), measured not modeled
        audit_on_s: list[float] = []
        audit_off_s: list[float] = []
        # rate 0 means the server built NO auditor (the flagless
        # byte-identity posture) — there is nothing to A/B
        if args.audit_rate and servers[0].auditor is not None:
            ab_pairs = max(cold_n, 5)
            for _ in range(ab_pairs):
                for rate, sink in ((args.audit_rate, audit_on_s),
                                   (0.0, audit_off_s)):
                    for srv in servers:
                        srv.auditor.set_rate(rate)
                    t0 = time.perf_counter()
                    r = client.submit(*paths)
                    sink.append(time.perf_counter() - t0)
                    if r.fasta != seq_results[0].fasta:
                        raise SystemExit("[servebench] audit A/B run "
                                         "diverged from the audited "
                                         "run")
            for srv in servers:
                srv.auditor.set_rate(args.audit_rate)
            print(f"[servebench] audit A/B ({ab_pairs} interleaved "
                  f"pairs): on {statistics.mean(audit_on_s):.2f}s vs "
                  f"off {statistics.mean(audit_off_s):.2f}s mean",
                  file=sys.stderr)

        # ---- warm concurrent wave: the multiplexing story, fully
        # streamed — every wave job asks for live progress AND streamed
        # result parts, so both time-to-first-progress and
        # time-to-first-BYTE (first polished contig on the wire) are
        # measured under contention, not just on an idle server
        results: list = [None] * args.jobs
        latencies: list = [0.0] * args.jobs
        first_progress: list = [None] * args.jobs
        first_byte: list = [None] * args.jobs

        def submit(i):
            t = time.perf_counter()

            def on_progress(ev, _i=i, _t=t):
                if first_progress[_i] is None:
                    first_progress[_i] = time.perf_counter() - _t

            def on_part(frame, _i=i, _t=t):
                if first_byte[_i] is None:
                    first_byte[_i] = time.perf_counter() - _t

            results[i] = clients[i % n_replicas].submit(
                *paths, retries=5, on_progress=on_progress,
                on_part=on_part)
            latencies[i] = time.perf_counter() - t

        # ---- fleet mode: the aggregator polls every replica's scrape
        # + healthz MID-WAVE (the overhead must be measured under
        # load, not on an idle server); each poll records its own
        # wall (aggregator lag) and the per-replica scrape times
        fleet_polls: list[dict] = []
        agg = None
        stop_polling = threading.Event()

        def poll_fleet():
            while not stop_polling.is_set():
                try:
                    snap = agg.poll()
                    fleet_polls.append(
                        {"poll_s": snap.poll_s,
                         "healthy": snap.healthy,
                         "scrape_s": sum(r.scrape_s
                                         for r in snap.replicas)})
                except Exception as exc:  # noqa: BLE001
                    fleet_polls.append({"error": str(exc)})
                stop_polling.wait(args.fleet_poll_s)

        poller = None
        if args.fleet:
            from racon_tpu.obs.fleet import FleetAggregator

            agg = FleetAggregator([s.config.socket_path
                                   for s in servers])
            poller = threading.Thread(target=poll_fleet, daemon=True)

        threads = [threading.Thread(target=submit, args=(i,))
                   for i in range(args.jobs)]
        # replica-side scrape cost baseline: the servers self-meter
        # their exposition-render seconds (wire and aggregator-side
        # parse time are the aggregator's cost, not the replicas')
        scrape_render_pre = sum(s._scrape_render_s for s in servers)
        t_wave = time.perf_counter()
        if poller is not None:
            poller.start()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wave_s = time.perf_counter() - t_wave
        if poller is not None:
            stop_polling.set()
            poller.join(timeout=5)
        scrape_render_s = (sum(s._scrape_render_s for s in servers)
                           - scrape_render_pre)

        # ---- open-loop arrival sweep (--qps): Poisson arrivals on the
        # SAME warm server — the saturation-knee curve
        openloop: list[dict] = []
        if args.qps is not None or args.qps_curve:
            rates = []
            if args.qps_curve:
                rates += [float(r) for r in args.qps_curve.split(",")
                          if r.strip()]
            if args.qps is not None and args.qps not in rates:
                rates.append(args.qps)
            for k, rate in enumerate(sorted(set(rates))):
                pt = run_openloop(client, paths, rate, args.qps_jobs,
                                  seed=args.seed + k)
                openloop.append(pt)
                print(f"[servebench] openloop qps={rate:g}: "
                      f"p50 {pt.get('p50_s', float('nan')):.2f}s "
                      f"p99 {pt.get('p99_s', float('nan')):.2f}s "
                      f"ttfb_p50 {pt.get('ttfb_p50_s', float('nan')):.2f}s "
                      f"achieved {pt['achieved_qps']:g}/{rate:g}",
                      file=sys.stderr)

        # every replica's numbers reach the artifact: the gated SLO
        # counters and batcher activity aggregate across the fleet
        snap = merge_fleet_snaps([s.stats_snapshot() for s in servers])
        audit_snaps = [s.auditor.snapshot() for s in servers
                       if s.auditor is not None]
        for srv in servers:
            srv.drain(timeout=30)

        # ---- journal consistency: every journaled job reaches exactly
        # one terminal state, started/terminal pairs balance — per
        # replica journal (job ids restart per server, so the files
        # must be checked separately, not concatenated)
        from obsreport import check_parts_streamed
        from racon_tpu.obs.journal import check_consistency, read_journal

        journal_entries = []
        journal_problems = []
        for jp in journal_paths:
            entries = read_journal(jp)
            journal_entries += entries
            # lifecycle invariants PLUS the streamed-results receipt
            # (one part-streamed line per output contig) — the same
            # pair obsreport --check enforces
            journal_problems += (check_consistency(entries)
                                 + check_parts_streamed(entries))

    # ---- analysis
    from racon_tpu.serve.queue import nearest_rank

    fail: list[str] = []
    all_results = seq_results + results
    warm_sorted = sorted(latencies)
    p50 = nearest_rank(warm_sorted, 0.50)
    p95 = nearest_rank(warm_sorted, 0.95)
    p99 = nearest_rank(warm_sorted, 0.99)
    seq_p50 = nearest_rank(sorted(seq_s), 0.50)
    cold_p50 = nearest_rank(sorted(cold_s), 0.50)
    compiles_per_job = [
        (r.serve.get("batch") or {}).get("compiles", 0)
        for r in all_results]
    queue_waits = [r.serve["queue_wait_s"] for r in results]
    exec_s = [r.serve["exec_s"] for r in results]

    if cold_out is not None and any(r.fasta != cold_out
                                    for r in all_results):
        fail.append("warm output diverged from cold CLI bytes")
    if any(compiles_per_job):
        fail.append(f"warm jobs compiled: {compiles_per_job}")
    if seq_p50 >= cold_p50:
        fail.append(f"warm p50 {seq_p50:.2f}s did not beat cold p50 "
                    f"{cold_p50:.2f}s")
    ttfp = [v for v in first_progress if v is not None]
    if len(ttfp) < args.jobs:
        fail.append(f"only {len(ttfp)}/{args.jobs} wave jobs received "
                    "a progress frame before their result")
    ttfp_p50 = nearest_rank(sorted(ttfp), 0.50) if ttfp else None
    ttfb = [v for v in first_byte if v is not None]
    if len(ttfb) < args.jobs:
        fail.append(f"only {len(ttfb)}/{args.jobs} wave jobs received "
                    "a result_part frame before their result")
    ttfb_p50 = nearest_rank(sorted(ttfb), 0.50) if ttfb else None
    for p in journal_problems:
        fail.append(f"journal inconsistency: {p}")
    # ---- fleet columns: aggregator lag (one poll's scrape+parse+merge
    # wall) and scrape overhead (replica time spent answering the
    # aggregator as a fraction of the wave — the <2% budget perfgate
    # holds the observability plane to)
    fleet_block = None
    if args.fleet:
        good = [p for p in fleet_polls if "poll_s" in p]
        poll_errors = [p["error"] for p in fleet_polls if "error" in p]
        if not good:
            fail.append("fleet aggregator never completed a poll "
                        f"mid-wave ({poll_errors[:3]})")
        else:
            lags = sorted(p["poll_s"] for p in good)
            # overhead = the replicas' OWN exposition-render seconds
            # (self-metered) over the replica-seconds of wave wall —
            # what answering the aggregator actually cost the fleet
            overhead_pct = (scrape_render_s / max(wave_s, 1e-9)
                            / n_replicas * 100.0)
            unhealthy = sum(1 for p in good if not p["healthy"])
            fleet_block = {
                "replicas": n_replicas,
                "polls": len(good),
                "poll_errors": len(poll_errors),
                "agg_lag_p50_s": round(nearest_rank(lags, 0.50), 5),
                "agg_lag_max_s": round(lags[-1], 5),
                "scrape_render_s": round(scrape_render_s, 4),
                "scrape_overhead_pct": round(overhead_pct, 3),
                "unhealthy_polls": unhealthy,
            }
            if unhealthy or poll_errors:
                fail.append(
                    f"fleet aggregator saw {unhealthy} unhealthy and "
                    f"{len(poll_errors)} failed polls mid-wave — every "
                    "replica must answer scrape+healthz under load")
    # ---- audit overhead columns (--audit-rate): sampled fraction,
    # shadow device seconds, and the measured A/B wall delta — the
    # number perfgate holds to the <2% observability budget
    audit_block = None
    if args.audit_rate is not None and audit_snaps:
        def _tot(key):
            return sum(a[key] for a in audit_snaps)

        on_mean = statistics.mean(audit_on_s or seq_s)
        off_mean = statistics.mean(audit_off_s) if audit_off_s else 0.0
        overhead_pct = ((on_mean / off_mean - 1.0) * 100.0
                        if off_mean > 0 else 0.0)
        audit_block = {
            "rate": args.audit_rate,
            "windows": _tot("windows"),
            "sampled": _tot("sampled"),
            "sampled_frac": round(_tot("sampled")
                                  / max(1, _tot("windows")), 4),
            "audited": _tot("audited"),
            "mismatches": _tot("mismatches"),
            "demotions": _tot("demotions"),
            "repaired": _tot("repaired"),
            "shadow_s": round(_tot("shadow_s"), 4),
            "overhead_pct": round(overhead_pct, 3),
            "ab_runs": len(audit_on_s),
            "seq_mean_on_s": round(on_mean, 4),
            "seq_mean_off_s": round(off_mean, 4),
        }
        if audit_block["mismatches"]:
            # a mismatch on this clean synthetic workload is a REAL
            # silent-corruption (or oracle) bug, never acceptable noise
            fail.append(f"audit sentinel caught "
                        f"{audit_block['mismatches']} mismatches on a "
                        "clean bench workload")
    baseline = None
    if args.baseline:
        try:
            with open(args.baseline) as fh:
                baseline = _baseline_view(json.load(fh))
        except (OSError, ValueError) as exc:
            fail.append(f"unreadable --baseline {args.baseline}: {exc}")

    b = snap["batcher"]
    print(f"[servebench] warm sequential: p50 {seq_p50:.2f}s vs cold "
          f"p50 {cold_p50:.2f}s (speedup "
          f"x{cold_p50 / max(seq_p50, 1e-9):.1f}) "
          f"[{'OK' if seq_p50 < cold_p50 else 'FAIL'}]", file=sys.stderr)
    print(f"[servebench] warm concurrent: {args.jobs} jobs in "
          f"{wave_s:.2f}s ({wave_s / args.jobs:.2f}s/job) — latency "
          f"p50 {p50:.2f}s p95 {p95:.2f}s p99 {p99:.2f}s mean "
          f"{statistics.mean(latencies):.2f}s", file=sys.stderr)
    print(f"[servebench] cold: {len(cold_s)} runs — p50 {cold_p50:.2f}s "
          f"mean {statistics.mean(cold_s):.2f}s", file=sys.stderr)
    print(f"[servebench] compiles/job after warmup: {compiles_per_job} "
          f"[{'OK' if not any(compiles_per_job) else 'FAIL'} target 0]",
          file=sys.stderr)
    print(f"[servebench] queue wait mean {statistics.mean(queue_waits):.3f}s "
          f"max {max(queue_waits):.3f}s; exec mean "
          f"{statistics.mean(exec_s):.3f}s", file=sys.stderr)
    if ttfp:
        print(f"[servebench] time-to-first-progress: p50 "
              f"{ttfp_p50:.3f}s max {max(ttfp):.3f}s "
              f"({len(ttfp)}/{args.jobs} jobs) "
              f"[{'OK' if len(ttfp) == args.jobs else 'FAIL'}]",
              file=sys.stderr)
    if ttfb:
        print(f"[servebench] time-to-first-byte (streamed part): p50 "
              f"{ttfb_p50:.3f}s max {max(ttfb):.3f}s vs job p50 "
              f"{p50:.3f}s ({len(ttfb)}/{args.jobs} jobs) "
              f"[{'OK' if len(ttfb) == args.jobs else 'FAIL'}]",
              file=sys.stderr)
    if baseline and baseline.get("p99_s"):
        worst = (max((pt for pt in openloop if pt.get("p99_s")),
                     key=lambda pt: pt["qps"], default=None)
                 if openloop else None)
        cand_p99 = worst["p99_s"] if worst else p99
        cand_ttfb = (worst.get("ttfb_p50_s")
                     if worst else ttfb_p50)
        delta = (1 - cand_p99 / baseline["p99_s"]) * 100
        print(f"[servebench] vs baseline "
              f"({baseline.get('design') or 'prior'}): p99 "
              f"{cand_p99:.2f}s vs {baseline['p99_s']:.2f}s "
              f"({abs(delta):.0f}% {'better' if delta >= 0 else 'WORSE'})"
              + (f", ttfb_p50 {cand_ttfb:.2f}s vs "
                 f"{baseline['ttfb_p50_s']:.2f}s"
                 if cand_ttfb and baseline.get("ttfb_p50_s")
                 else ""), file=sys.stderr)
    if audit_block:
        print(f"[servebench] audit: rate {audit_block['rate']:g} — "
              f"{audit_block['sampled']}/{audit_block['windows']} "
              f"windows sampled "
              f"({audit_block['sampled_frac'] * 100:.1f}%), shadow "
              f"{audit_block['shadow_s']:.3f}s, "
              f"{audit_block['mismatches']} mismatches, overhead "
              f"{audit_block['overhead_pct']:+.2f}% "
              f"[{'OK' if audit_block['overhead_pct'] <= 2.0 else 'FAIL'} "
              "budget 2%]", file=sys.stderr)
    if fleet_block:
        print(f"[servebench] fleet: {n_replicas} replicas, "
              f"{fleet_block['polls']} aggregator polls mid-wave — "
              f"lag p50 {fleet_block['agg_lag_p50_s'] * 1e3:.1f}ms "
              f"max {fleet_block['agg_lag_max_s'] * 1e3:.1f}ms, "
              f"scrape overhead "
              f"{fleet_block['scrape_overhead_pct']:.2f}% "
              f"[{'OK' if fleet_block['scrape_overhead_pct'] < 2.0 else 'FAIL'} "
              "budget 2%]", file=sys.stderr)
    n_journal_jobs = len({e.get('job') for e in journal_entries
                          if e.get('job')})
    print(f"[servebench] journal: {len(journal_entries)} events / "
          f"{n_journal_jobs} jobs, "
          f"{len(journal_problems)} consistency problems "
          f"[{'OK' if not journal_problems else 'FAIL'}]",
          file=sys.stderr)
    print(f"[servebench] device iterations: {b['iterations']} "
          f"({b['shared_iterations']} cross-job, max "
          f"{b['max_jobs_in_iteration']} jobs / "
          f"{b['max_windows_in_iteration']} windows per iteration)",
          file=sys.stderr)
    # measured per-iteration host overhead (iteration wall - the
    # pipeline's device-stage seconds) — the dispatch-loop number
    shared_its = b["iterations"] - b.get("solo_iterations", 0)
    if shared_its > 0 and "host_s" in b:
        print(f"[servebench] dispatch host overhead: "
              f"{b['host_s']:.3f}s total, "
              f"{b['host_s'] / shared_its * 1e3:.1f}ms per feeder "
              "iteration", file=sys.stderr)
    lanes = b.get("lanes") or []
    # fleet mode concatenates per-replica lane rows: the multi-lane
    # overlap gate applies only when some single replica actually
    # partitioned its mesh (N single-lane replicas are not "2 lanes")
    lanes_per_replica: dict = {}
    for ln in lanes:
        rep = ln.get("replica", 0)
        lanes_per_replica[rep] = lanes_per_replica.get(rep, 0) + 1
    if max(lanes_per_replica.values(), default=0) > 1:
        per_lane = ", ".join(
            f"lane {ln['lane']} ({ln['n_devices']} dev): "
            f"{ln['iterations']} its / {ln['busy_s']:.2f}s busy"
            for ln in lanes)
        concurrent = b.get("max_concurrent_iterations", 0)
        print(f"[servebench] worker lanes: {per_lane}; max "
              f"{concurrent} iterations concurrent "
              f"[{'OK' if concurrent >= 2 else 'FAIL'} overlap]",
              file=sys.stderr)
        if concurrent < 2:
            fail.append("worker lanes never ran iterations "
                        "concurrently (max_concurrent_iterations "
                        f"{concurrent})")
    elif args.worker_lanes is not None and args.worker_lanes > 1:
        # the lane partition clamped away (e.g. an inherited XLA_FLAGS
        # pinning a 1-device mesh): the promised overlap gate cannot
        # run — that must FAIL loudly, not silently pass
        fail.append(f"--worker-lanes {args.worker_lanes} requested but "
                    f"the server ran "
                    f"{max(lanes_per_replica.values(), default=1)} "
                    "lane(s) — the device mesh was too small to "
                    "partition")
    for engine, e in (b.get("occupancy") or {}).items():
        if e.get("buckets"):
            print(f"[servebench] {engine} occupancy "
                  f"{e['occupancy_pct']:.1f}% across "
                  f"{len(e['buckets'])} shapes", file=sys.stderr)

    if args.json:
        artifact = {
            "mode": "serve",
            "jobs": args.jobs,
            "warm": {"seq_p50_s": round(seq_p50, 3),
                     "p50_s": round(p50, 3), "p95_s": round(p95, 3),
                     "p99_s": round(p99, 3),
                     "mean_s": round(statistics.mean(latencies), 3),
                     "wave_s": round(wave_s, 3),
                     "warmup_s": round(warm_ready_s, 3),
                     "queue_wait_mean_s": round(
                         statistics.mean(queue_waits), 4),
                     "ttfp_p50_s": (round(ttfp_p50, 4)
                                    if ttfp_p50 is not None else None),
                     "ttfp_max_s": (round(max(ttfp), 4)
                                    if ttfp else None),
                     "ttfb_p50_s": (round(ttfb_p50, 4)
                                    if ttfb_p50 is not None else None),
                     "ttfb_max_s": (round(max(ttfb), 4)
                                    if ttfb else None),
                     "compiles_per_job": compiles_per_job},
            "slo": {k: (snap.get("slo") or {}).get(k) for k in
                    ("deadline_hit", "deadline_miss", "expired",
                     "miss_rate")},
            "journal": {"events": len(journal_entries),
                        "jobs": n_journal_jobs,
                        "consistent": not journal_problems},
            "cold": {"runs": len(cold_s),
                     "p50_s": round(cold_p50, 3),
                     "mean_s": round(statistics.mean(cold_s), 3)},
            "speedup_p50": round(cold_p50 / max(seq_p50, 1e-9), 2),
            "iterations": {k: b[k] for k in
                           ("iterations", "shared_iterations", "jobs",
                            "windows", "max_jobs_in_iteration",
                            "max_windows_in_iteration",
                            "max_concurrent_iterations", "host_s")},
            "lanes": b.get("lanes") or [],
            "mesh": _mesh_block(b),
            "occupancy": b.get("occupancy", {}),
            "metrics": {"queue": snap["queue"],
                        "batcher": {k: v for k, v in b.items()
                                    if k != "occupancy"}},
            "pass": not fail,
        }
        if audit_block:
            artifact["audit"] = audit_block
        if fleet_block:
            artifact["fleet"] = fleet_block
        if openloop:
            artifact["openloop"] = {"curve": openloop,
                                    "jobs_per_rate": args.qps_jobs,
                                    "knee_qps": saturation_knee(
                                        openloop)}
        if baseline is not None:
            artifact["baseline"] = baseline
        with open(args.json, "w") as fh:
            json.dump(artifact, fh, indent=2, sort_keys=True)
        print(f"[servebench] wrote {args.json}", file=sys.stderr)

    if fail:
        for f in fail:
            print(f"[servebench] FAIL: {f}", file=sys.stderr)
        return 1
    print("[servebench] PASS", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
