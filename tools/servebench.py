"""Serve-mode benchmark: warm server submits vs cold one-shot CLI runs.

Starts a `PolishServer` (warmed on the benchmark's own inputs, so job
shapes hit the warm jit caches exactly), submits N concurrent synthetic
jobs through `PolishClient`, and compares against N sequential COLD CLI
runs — fresh `python -m racon_tpu.cli` subprocesses, each paying
interpreter + import + engine construction + compile, which is precisely
the per-run tax the serve subsystem amortizes.

Two warm phases measure two different claims:

  - SEQUENTIAL warm submits (one at a time — the like-for-like twin of
    the sequential cold runs, same machine utilization): their p50 is
    the headline warm latency and must beat the cold p50;
  - a CONCURRENT wave of N submits: cross-job batch rounds, queue-wait
    vs execution breakdown, and batch occupancy — the multiplexing
    story (concurrent p50 embeds queueing on an oversubscribed host, so
    it is reported, not gated).

Exit status is the acceptance check: 0 only when sequential warm p50
beats cold p50, no warm job compiled anything (sched compile telemetry:
the warm path recompiles NOTHING), and every warm job's FASTA equals
the cold CLI bytes. `--json PATH` writes the summary as a bench-style
artifact with `occupancy` / `metrics` fields alongside the serve
numbers (the same field names bench.py publishes).

    python tools/servebench.py --jobs 4 [--genome-kb 20] [--json out.json]
"""

from __future__ import annotations

import argparse
import gzip
import json
import os
import statistics
import subprocess
import sys
import tempfile
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                      "/tmp/racon_tpu_jax_cache")
sys.path = [p for p in sys.path if "axon_site" not in p]
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))


def build_dataset(tmpdir: str, genome_kb: int, coverage: int,
                  read_len: int, seed: int):
    """Synthetic ONT-style workload via synthbench's simulator (same
    error model as the scale bench, so serve numbers are comparable)."""
    import random

    from synthbench import simulate

    rng = random.Random(seed)
    _, draft, reads, paf = simulate(rng, genome_kb * 1000, coverage,
                                    read_len, 0.12, 0.10)
    paths = (os.path.join(tmpdir, "reads.fasta.gz"),
             os.path.join(tmpdir, "ovl.paf.gz"),
             os.path.join(tmpdir, "draft.fasta.gz"))
    with gzip.open(paths[0], "wb", compresslevel=1) as f:
        for name, read in reads:
            f.write(b">" + name.encode() + b"\n" + read + b"\n")
    with gzip.open(paths[1], "wb", compresslevel=1) as f:
        f.write(("\n".join(paf) + "\n").encode())
    with gzip.open(paths[2], "wb", compresslevel=1) as f:
        f.write(b">draft\n" + draft + b"\n")
    return paths


def cold_cli_run(paths, args) -> tuple[float, bytes]:
    """One fresh-process CLI run: the full cold tax, wall-clocked."""
    env = {k: v for k, v in os.environ.items() if "axon" not in k.lower()}
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = os.pathsep.join(
        [REPO] + [p for p in env.get("PYTHONPATH", "").split(os.pathsep)
                  if p and "axon_site" not in p])
    cmd = [sys.executable, "-m", "racon_tpu.cli",
           "-t", str(args.threads)]
    if args.tpupoa_batches:
        cmd += ["-c", str(args.tpupoa_batches)]
    if args.tpualigner_batches:
        cmd += ["--tpualigner-batches", str(args.tpualigner_batches)]
    cmd += list(paths)
    t0 = time.perf_counter()
    proc = subprocess.run(cmd, env=env, capture_output=True)
    dt = time.perf_counter() - t0
    if proc.returncode != 0:
        print(proc.stderr.decode(errors="replace"), file=sys.stderr)
        raise SystemExit(f"[servebench] cold CLI run failed "
                         f"(rc {proc.returncode})")
    return dt, proc.stdout


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--jobs", type=int, default=4,
                    help="concurrent warm submissions")
    ap.add_argument("--cold-runs", type=int, default=None,
                    help="sequential cold CLI runs to time "
                         "(default min(jobs, 3))")
    ap.add_argument("--genome-kb", type=int, default=20)
    ap.add_argument("--coverage", type=int, default=20)
    ap.add_argument("--read-len", type=int, default=4000)
    ap.add_argument("--seed", type=int, default=42)
    ap.add_argument("-t", "--threads", type=int, default=2)
    ap.add_argument("-c", "--tpupoa-batches", type=int, default=0)
    ap.add_argument("--tpualigner-batches", type=int, default=0)
    ap.add_argument("--workers", type=int, default=2)
    ap.add_argument("--json", default=None,
                    help="write the bench-style JSON artifact here")
    args = ap.parse_args(argv)

    from racon_tpu.serve import PolishClient, PolishServer

    cold_n = args.cold_runs if args.cold_runs is not None \
        else min(args.jobs, 3)

    with tempfile.TemporaryDirectory(prefix="racon_servebench_") as tmp:
        print(f"[servebench] simulating {args.genome_kb} kb at "
              f"{args.coverage}x ...", file=sys.stderr)
        paths = build_dataset(tmp, args.genome_kb, args.coverage,
                              args.read_len, args.seed)

        # ---- cold: N sequential fresh-process CLI runs
        cold_s: list[float] = []
        cold_out = None
        for i in range(cold_n):
            dt, out = cold_cli_run(paths, args)
            cold_s.append(dt)
            cold_out = out
            print(f"[servebench] cold run {i + 1}/{cold_n}: {dt:.2f}s",
                  file=sys.stderr)

        # ---- warm: one server, N concurrent submissions
        sock = os.path.join(tmp, "serve.sock")
        server = PolishServer(
            socket_path=sock, workers=args.workers, warmup=False,
            job_threads=args.threads,
            tpu_poa_batches=args.tpupoa_batches,
            tpu_aligner_batches=args.tpualigner_batches)
        t0 = time.perf_counter()
        server.warmup(paths=paths)  # warm on the SAME shapes jobs use
        server.start()
        warm_ready_s = time.perf_counter() - t0
        print(f"[servebench] server warm in {warm_ready_s:.2f}s "
              f"({server._warm['compiles']} compiles "
              f"{server._warm['compile_s']:.2f}s)", file=sys.stderr)

        client = PolishClient(socket_path=sock)

        # ---- warm sequential: like-for-like vs the cold runs
        seq_s: list[float] = []
        seq_results: list = []
        for i in range(cold_n):
            t0 = time.perf_counter()
            seq_results.append(client.submit(*paths))
            seq_s.append(time.perf_counter() - t0)
            print(f"[servebench] warm seq run {i + 1}/{cold_n}: "
                  f"{seq_s[-1]:.2f}s", file=sys.stderr)

        # ---- warm concurrent wave: the multiplexing story
        results: list = [None] * args.jobs
        latencies: list = [0.0] * args.jobs

        def submit(i):
            t = time.perf_counter()
            results[i] = client.submit(*paths, retries=5)
            latencies[i] = time.perf_counter() - t

        threads = [threading.Thread(target=submit, args=(i,))
                   for i in range(args.jobs)]
        t_wave = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wave_s = time.perf_counter() - t_wave

        snap = server.stats_snapshot()
        server.drain(timeout=30)

    # ---- analysis
    fail: list[str] = []
    all_results = seq_results + results
    warm_sorted = sorted(latencies)
    p50 = warm_sorted[len(warm_sorted) // 2]
    p95 = warm_sorted[min(len(warm_sorted) - 1,
                          int(len(warm_sorted) * 0.95))]
    seq_p50 = sorted(seq_s)[len(seq_s) // 2]
    cold_p50 = sorted(cold_s)[len(cold_s) // 2]
    compiles_per_job = [
        (r.serve.get("batch") or {}).get("compiles", 0)
        for r in all_results]
    queue_waits = [r.serve["queue_wait_s"] for r in results]
    exec_s = [r.serve["exec_s"] for r in results]

    if cold_out is not None and any(r.fasta != cold_out
                                    for r in all_results):
        fail.append("warm output diverged from cold CLI bytes")
    if any(compiles_per_job):
        fail.append(f"warm jobs compiled: {compiles_per_job}")
    if seq_p50 >= cold_p50:
        fail.append(f"warm p50 {seq_p50:.2f}s did not beat cold p50 "
                    f"{cold_p50:.2f}s")

    b = snap["batcher"]
    print(f"[servebench] warm sequential: p50 {seq_p50:.2f}s vs cold "
          f"p50 {cold_p50:.2f}s (speedup "
          f"x{cold_p50 / max(seq_p50, 1e-9):.1f}) "
          f"[{'OK' if seq_p50 < cold_p50 else 'FAIL'}]", file=sys.stderr)
    print(f"[servebench] warm concurrent: {args.jobs} jobs in "
          f"{wave_s:.2f}s ({wave_s / args.jobs:.2f}s/job) — latency "
          f"p50 {p50:.2f}s p95 {p95:.2f}s mean "
          f"{statistics.mean(latencies):.2f}s", file=sys.stderr)
    print(f"[servebench] cold: {len(cold_s)} runs — p50 {cold_p50:.2f}s "
          f"mean {statistics.mean(cold_s):.2f}s", file=sys.stderr)
    print(f"[servebench] compiles/job after warmup: {compiles_per_job} "
          f"[{'OK' if not any(compiles_per_job) else 'FAIL'} target 0]",
          file=sys.stderr)
    print(f"[servebench] queue wait mean {statistics.mean(queue_waits):.3f}s "
          f"max {max(queue_waits):.3f}s; exec mean "
          f"{statistics.mean(exec_s):.3f}s", file=sys.stderr)
    print(f"[servebench] batch rounds: {b['rounds']} "
          f"({b['multi_job_rounds']} cross-job, max "
          f"{b['max_jobs_in_round']} jobs/round)", file=sys.stderr)
    for engine, e in (b.get("occupancy") or {}).items():
        if e.get("buckets"):
            print(f"[servebench] {engine} occupancy "
                  f"{e['occupancy_pct']:.1f}% across "
                  f"{len(e['buckets'])} shapes", file=sys.stderr)

    if args.json:
        artifact = {
            "mode": "serve",
            "jobs": args.jobs,
            "warm": {"seq_p50_s": round(seq_p50, 3),
                     "p50_s": round(p50, 3), "p95_s": round(p95, 3),
                     "mean_s": round(statistics.mean(latencies), 3),
                     "wave_s": round(wave_s, 3),
                     "warmup_s": round(warm_ready_s, 3),
                     "queue_wait_mean_s": round(
                         statistics.mean(queue_waits), 4),
                     "compiles_per_job": compiles_per_job},
            "cold": {"runs": len(cold_s),
                     "p50_s": round(cold_p50, 3),
                     "mean_s": round(statistics.mean(cold_s), 3)},
            "speedup_p50": round(cold_p50 / max(seq_p50, 1e-9), 2),
            "batch_rounds": {k: b[k] for k in
                             ("rounds", "multi_job_rounds", "jobs",
                              "windows", "max_jobs_in_round")},
            "occupancy": b.get("occupancy", {}),
            "metrics": {"queue": snap["queue"],
                        "batcher": {k: v for k, v in b.items()
                                    if k != "occupancy"}},
            "pass": not fail,
        }
        with open(args.json, "w") as fh:
            json.dump(artifact, fh, indent=2, sort_keys=True)
        print(f"[servebench] wrote {args.json}", file=sys.stderr)

    if fail:
        for f in fail:
            print(f"[servebench] FAIL: {f}", file=sys.stderr)
        return 1
    print("[servebench] PASS", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
