"""Serve-mode benchmark: warm server submits vs cold one-shot CLI runs.

Starts a `PolishServer` (warmed on the benchmark's own inputs, so job
shapes hit the warm jit caches exactly), submits N concurrent synthetic
jobs through `PolishClient`, and compares against N sequential COLD CLI
runs — fresh `python -m racon_tpu.cli` subprocesses, each paying
interpreter + import + engine construction + compile, which is precisely
the per-run tax the serve subsystem amortizes.

Two warm phases measure two different claims:

  - SEQUENTIAL warm submits (one at a time — the like-for-like twin of
    the sequential cold runs, same machine utilization): their p50 is
    the headline warm latency and must beat the cold p50;
  - a CONCURRENT wave of N submits: cross-job batch rounds, queue-wait
    vs execution breakdown, and batch occupancy — the multiplexing
    story (concurrent p50 embeds queueing on an oversubscribed host, so
    it is reported, not gated).

Exit status is the acceptance check: 0 only when sequential warm p50
beats cold p50, no warm job compiled anything (sched compile telemetry:
the warm path recompiles NOTHING), every warm job's FASTA equals the
cold CLI bytes, every wave job saw at least one live progress frame
before its result (time-to-first-progress is reported as its own
column), and the serve event journal — enabled for the measured run —
passes its consistency check (every job exactly one terminal state,
started/terminal pairs balanced). `--json PATH` writes the summary as a
bench-style artifact with `occupancy` / `metrics` / `slo` / `journal`
fields alongside the serve numbers (the same field names bench.py
publishes; tools/perfgate.py gates warm p50 and slo.miss_rate from it).

    python tools/servebench.py --jobs 4 [--genome-kb 20] [--json out.json]
"""

from __future__ import annotations

import argparse
import gzip
import json
import os
import statistics
import subprocess
import sys
import tempfile
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                      "/tmp/racon_tpu_jax_cache")
sys.path = [p for p in sys.path if "axon_site" not in p]
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))


def build_dataset(tmpdir: str, genome_kb: int, coverage: int,
                  read_len: int, seed: int):
    """Synthetic ONT-style workload via synthbench's simulator (same
    error model as the scale bench, so serve numbers are comparable)."""
    import random

    from synthbench import simulate

    rng = random.Random(seed)
    _, draft, reads, paf = simulate(rng, genome_kb * 1000, coverage,
                                    read_len, 0.12, 0.10)
    paths = (os.path.join(tmpdir, "reads.fasta.gz"),
             os.path.join(tmpdir, "ovl.paf.gz"),
             os.path.join(tmpdir, "draft.fasta.gz"))
    with gzip.open(paths[0], "wb", compresslevel=1) as f:
        for name, read in reads:
            f.write(b">" + name.encode() + b"\n" + read + b"\n")
    with gzip.open(paths[1], "wb", compresslevel=1) as f:
        f.write(("\n".join(paf) + "\n").encode())
    with gzip.open(paths[2], "wb", compresslevel=1) as f:
        f.write(b">draft\n" + draft + b"\n")
    return paths


def cold_cli_run(paths, args) -> tuple[float, bytes]:
    """One fresh-process CLI run: the full cold tax, wall-clocked."""
    env = {k: v for k, v in os.environ.items() if "axon" not in k.lower()}
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = os.pathsep.join(
        [REPO] + [p for p in env.get("PYTHONPATH", "").split(os.pathsep)
                  if p and "axon_site" not in p])
    cmd = [sys.executable, "-m", "racon_tpu.cli",
           "-t", str(args.threads)]
    if args.tpupoa_batches:
        cmd += ["-c", str(args.tpupoa_batches)]
    if args.tpualigner_batches:
        cmd += ["--tpualigner-batches", str(args.tpualigner_batches)]
    cmd += list(paths)
    t0 = time.perf_counter()
    proc = subprocess.run(cmd, env=env, capture_output=True)
    dt = time.perf_counter() - t0
    if proc.returncode != 0:
        print(proc.stderr.decode(errors="replace"), file=sys.stderr)
        raise SystemExit(f"[servebench] cold CLI run failed "
                         f"(rc {proc.returncode})")
    return dt, proc.stdout


def check_slo(args, PolishClient, PolishServer) -> int:
    """`--check-slo`: one warm server, one concurrent wave with per-job
    deadlines, three gated cells printed as a faultcheck-style row —
    p99 end-to-end latency, deadline-miss rate (from the server's OWN
    SLO accounting, the same numbers admission control uses), and a
    live `scrape` that must return Prometheus text with populated
    latency histograms. Exit 0 only when every cell passes."""
    with tempfile.TemporaryDirectory(prefix="racon_slo_") as tmp:
        print(f"[servebench] SLO gate: {args.jobs} jobs, deadline "
              f"{args.deadline:.0f}s, p99<= {args.slo_p99:.1f}s, "
              f"miss-rate<= {args.slo_miss_rate:.2f}", file=sys.stderr)
        paths = build_dataset(tmp, args.genome_kb, args.coverage,
                              args.read_len, args.seed)
        sock = os.path.join(tmp, "serve.sock")
        server = PolishServer(
            socket_path=sock, workers=args.workers, warmup=False,
            job_threads=args.threads,
            flight_dir=os.path.join(tmp, "flight"),
            tpu_poa_batches=args.tpupoa_batches,
            tpu_aligner_batches=args.tpualigner_batches)
        server.warmup(paths=paths)
        server.start()
        client = PolishClient(socket_path=sock)

        latencies = [None] * args.jobs

        def submit(i):
            t0 = time.perf_counter()
            try:
                client.submit(*paths, deadline_s=args.deadline,
                              retries=5)
            except Exception as exc:
                print(f"[servebench] job {i} failed: {exc}",
                      file=sys.stderr)
                return
            latencies[i] = time.perf_counter() - t0

        threads = [threading.Thread(target=submit, args=(i,))
                   for i in range(args.jobs)]
        for t in threads:
            t.start()
        # scrape mid-wave: the live-exposition contract is part of the
        # gate (must answer while jobs are executing)
        live = client.scrape()
        for t in threads:
            t.join()
        snap = client.stats()
        server.drain(timeout=30)

    from racon_tpu.serve.queue import nearest_rank

    cells = []
    done = sorted(v for v in latencies if v is not None)
    if len(done) < args.jobs:
        cells.append(("completed", False,
                      f"{len(done)}/{args.jobs} jobs"))
    p99 = nearest_rank(done, 0.99) if done else float("inf")
    cells.append(("p99", p99 <= args.slo_p99,
                  f"{p99:.2f}s <= {args.slo_p99:.1f}s"))
    slo = snap.get("slo") or {}
    miss_rate = float(slo.get("miss_rate", 1.0))
    cells.append(("miss-rate", miss_rate <= args.slo_miss_rate,
                  f"{miss_rate:.2f} <= {args.slo_miss_rate:.2f} "
                  f"({slo.get('deadline_miss', '?')} missed, "
                  f"{slo.get('expired', '?')} expired)"))
    hist_lines = [ln for ln in live.splitlines()
                  if "_bucket{" in ln]
    populated = any(not ln.rstrip().endswith(" 0")
                    for ln in hist_lines)
    cells.append(("scrape", bool(hist_lines) and populated,
                  f"{len(live.splitlines())} lines, "
                  f"{len(hist_lines)} buckets, "
                  f"{'populated' if populated else 'EMPTY'}"))
    row = "  ".join(f"{name} {'pass' if ok else 'FAIL'} ({detail})"
                    for name, ok, detail in cells)
    failures = sum(not ok for _, ok, _ in cells)
    print(f"[servebench] slo  {row}", file=sys.stderr)
    print(f"[servebench] SLO gate "
          f"{'PASS' if not failures else 'FAIL'}: "
          f"{len(cells) - failures}/{len(cells)} cells green",
          file=sys.stderr)
    return 1 if failures else 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--jobs", type=int, default=4,
                    help="concurrent warm submissions")
    ap.add_argument("--cold-runs", type=int, default=None,
                    help="sequential cold CLI runs to time "
                         "(default min(jobs, 3))")
    ap.add_argument("--genome-kb", type=int, default=20)
    ap.add_argument("--coverage", type=int, default=20)
    ap.add_argument("--read-len", type=int, default=4000)
    ap.add_argument("--seed", type=int, default=42)
    ap.add_argument("-t", "--threads", type=int, default=2)
    ap.add_argument("-c", "--tpupoa-batches", type=int, default=0)
    ap.add_argument("--tpualigner-batches", type=int, default=0)
    ap.add_argument("--workers", type=int, default=2)
    ap.add_argument("--json", default=None,
                    help="write the bench-style JSON artifact here")
    ap.add_argument("--check-slo", action="store_true",
                    help="SLO gate mode: run a small concurrent wave "
                         "with per-job deadlines and assert p99 latency "
                         "/ deadline-miss-rate / scrape validity "
                         "(faultcheck-style pass/fail row, exit status "
                         "is the gate)")
    ap.add_argument("--slo-p99", type=float, default=60.0,
                    help="--check-slo: p99 end-to-end latency bound in "
                         "seconds (default 60)")
    ap.add_argument("--slo-miss-rate", type=float, default=0.0,
                    help="--check-slo: allowed deadline-miss rate "
                         "(default 0 — no misses)")
    ap.add_argument("--deadline", type=float, default=120.0,
                    help="--check-slo: per-job deadline_s attached to "
                         "every wave job (default 120)")
    args = ap.parse_args(argv)

    from racon_tpu.serve import PolishClient, PolishServer

    if args.check_slo:
        return check_slo(args, PolishClient, PolishServer)

    cold_n = args.cold_runs if args.cold_runs is not None \
        else min(args.jobs, 3)

    with tempfile.TemporaryDirectory(prefix="racon_servebench_") as tmp:
        print(f"[servebench] simulating {args.genome_kb} kb at "
              f"{args.coverage}x ...", file=sys.stderr)
        paths = build_dataset(tmp, args.genome_kb, args.coverage,
                              args.read_len, args.seed)

        # ---- cold: N sequential fresh-process CLI runs
        cold_s: list[float] = []
        cold_out = None
        for i in range(cold_n):
            dt, out = cold_cli_run(paths, args)
            cold_s.append(dt)
            cold_out = out
            print(f"[servebench] cold run {i + 1}/{cold_n}: {dt:.2f}s",
                  file=sys.stderr)

        # ---- warm: one server, N concurrent submissions. The event
        # journal rides the measured run (its <2% overhead is part of
        # the warm numbers, not hidden from them) and is consistency-
        # checked after drain as part of the gate
        sock = os.path.join(tmp, "serve.sock")
        journal_path = os.path.join(tmp, "journal.jsonl")
        server = PolishServer(
            socket_path=sock, workers=args.workers, warmup=False,
            job_threads=args.threads, journal=journal_path,
            tpu_poa_batches=args.tpupoa_batches,
            tpu_aligner_batches=args.tpualigner_batches)
        t0 = time.perf_counter()
        server.warmup(paths=paths)  # warm on the SAME shapes jobs use
        server.start()
        warm_ready_s = time.perf_counter() - t0
        print(f"[servebench] server warm in {warm_ready_s:.2f}s "
              f"({server._warm['compiles']} compiles "
              f"{server._warm['compile_s']:.2f}s)", file=sys.stderr)

        client = PolishClient(socket_path=sock)

        # ---- warm sequential: like-for-like vs the cold runs
        seq_s: list[float] = []
        seq_results: list = []
        for i in range(cold_n):
            t0 = time.perf_counter()
            seq_results.append(client.submit(*paths))
            seq_s.append(time.perf_counter() - t0)
            print(f"[servebench] warm seq run {i + 1}/{cold_n}: "
                  f"{seq_s[-1]:.2f}s", file=sys.stderr)

        # ---- warm concurrent wave: the multiplexing story, streamed —
        # every wave job asks for live progress so time-to-first-
        # progress (how long a client stares at nothing) is measured
        # under contention, not just on an idle server
        results: list = [None] * args.jobs
        latencies: list = [0.0] * args.jobs
        first_progress: list = [None] * args.jobs

        def submit(i):
            t = time.perf_counter()

            def on_progress(ev, _i=i, _t=t):
                if first_progress[_i] is None:
                    first_progress[_i] = time.perf_counter() - _t

            results[i] = client.submit(*paths, retries=5,
                                       on_progress=on_progress)
            latencies[i] = time.perf_counter() - t

        threads = [threading.Thread(target=submit, args=(i,))
                   for i in range(args.jobs)]
        t_wave = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wave_s = time.perf_counter() - t_wave

        snap = server.stats_snapshot()
        server.drain(timeout=30)

        # ---- journal consistency: every journaled job reaches exactly
        # one terminal state, started/terminal pairs balance
        from racon_tpu.obs.journal import check_consistency, read_journal

        journal_entries = read_journal(journal_path)
        journal_problems = check_consistency(journal_entries)

    # ---- analysis
    from racon_tpu.serve.queue import nearest_rank

    fail: list[str] = []
    all_results = seq_results + results
    warm_sorted = sorted(latencies)
    p50 = nearest_rank(warm_sorted, 0.50)
    p95 = nearest_rank(warm_sorted, 0.95)
    seq_p50 = nearest_rank(sorted(seq_s), 0.50)
    cold_p50 = nearest_rank(sorted(cold_s), 0.50)
    compiles_per_job = [
        (r.serve.get("batch") or {}).get("compiles", 0)
        for r in all_results]
    queue_waits = [r.serve["queue_wait_s"] for r in results]
    exec_s = [r.serve["exec_s"] for r in results]

    if cold_out is not None and any(r.fasta != cold_out
                                    for r in all_results):
        fail.append("warm output diverged from cold CLI bytes")
    if any(compiles_per_job):
        fail.append(f"warm jobs compiled: {compiles_per_job}")
    if seq_p50 >= cold_p50:
        fail.append(f"warm p50 {seq_p50:.2f}s did not beat cold p50 "
                    f"{cold_p50:.2f}s")
    ttfp = [v for v in first_progress if v is not None]
    if len(ttfp) < args.jobs:
        fail.append(f"only {len(ttfp)}/{args.jobs} wave jobs received "
                    "a progress frame before their result")
    ttfp_p50 = nearest_rank(sorted(ttfp), 0.50) if ttfp else None
    for p in journal_problems:
        fail.append(f"journal inconsistency: {p}")

    b = snap["batcher"]
    print(f"[servebench] warm sequential: p50 {seq_p50:.2f}s vs cold "
          f"p50 {cold_p50:.2f}s (speedup "
          f"x{cold_p50 / max(seq_p50, 1e-9):.1f}) "
          f"[{'OK' if seq_p50 < cold_p50 else 'FAIL'}]", file=sys.stderr)
    print(f"[servebench] warm concurrent: {args.jobs} jobs in "
          f"{wave_s:.2f}s ({wave_s / args.jobs:.2f}s/job) — latency "
          f"p50 {p50:.2f}s p95 {p95:.2f}s mean "
          f"{statistics.mean(latencies):.2f}s", file=sys.stderr)
    print(f"[servebench] cold: {len(cold_s)} runs — p50 {cold_p50:.2f}s "
          f"mean {statistics.mean(cold_s):.2f}s", file=sys.stderr)
    print(f"[servebench] compiles/job after warmup: {compiles_per_job} "
          f"[{'OK' if not any(compiles_per_job) else 'FAIL'} target 0]",
          file=sys.stderr)
    print(f"[servebench] queue wait mean {statistics.mean(queue_waits):.3f}s "
          f"max {max(queue_waits):.3f}s; exec mean "
          f"{statistics.mean(exec_s):.3f}s", file=sys.stderr)
    if ttfp:
        print(f"[servebench] time-to-first-progress: p50 "
              f"{ttfp_p50:.3f}s max {max(ttfp):.3f}s "
              f"({len(ttfp)}/{args.jobs} jobs) "
              f"[{'OK' if len(ttfp) == args.jobs else 'FAIL'}]",
              file=sys.stderr)
    n_journal_jobs = len({e.get('job') for e in journal_entries
                          if e.get('job')})
    print(f"[servebench] journal: {len(journal_entries)} events / "
          f"{n_journal_jobs} jobs, "
          f"{len(journal_problems)} consistency problems "
          f"[{'OK' if not journal_problems else 'FAIL'}]",
          file=sys.stderr)
    print(f"[servebench] batch rounds: {b['rounds']} "
          f"({b['multi_job_rounds']} cross-job, max "
          f"{b['max_jobs_in_round']} jobs/round)", file=sys.stderr)
    for engine, e in (b.get("occupancy") or {}).items():
        if e.get("buckets"):
            print(f"[servebench] {engine} occupancy "
                  f"{e['occupancy_pct']:.1f}% across "
                  f"{len(e['buckets'])} shapes", file=sys.stderr)

    if args.json:
        artifact = {
            "mode": "serve",
            "jobs": args.jobs,
            "warm": {"seq_p50_s": round(seq_p50, 3),
                     "p50_s": round(p50, 3), "p95_s": round(p95, 3),
                     "mean_s": round(statistics.mean(latencies), 3),
                     "wave_s": round(wave_s, 3),
                     "warmup_s": round(warm_ready_s, 3),
                     "queue_wait_mean_s": round(
                         statistics.mean(queue_waits), 4),
                     "ttfp_p50_s": (round(ttfp_p50, 4)
                                    if ttfp_p50 is not None else None),
                     "ttfp_max_s": (round(max(ttfp), 4)
                                    if ttfp else None),
                     "compiles_per_job": compiles_per_job},
            "slo": {k: (snap.get("slo") or {}).get(k) for k in
                    ("deadline_hit", "deadline_miss", "expired",
                     "miss_rate")},
            "journal": {"events": len(journal_entries),
                        "jobs": n_journal_jobs,
                        "consistent": not journal_problems},
            "cold": {"runs": len(cold_s),
                     "p50_s": round(cold_p50, 3),
                     "mean_s": round(statistics.mean(cold_s), 3)},
            "speedup_p50": round(cold_p50 / max(seq_p50, 1e-9), 2),
            "batch_rounds": {k: b[k] for k in
                             ("rounds", "multi_job_rounds", "jobs",
                              "windows", "max_jobs_in_round")},
            "occupancy": b.get("occupancy", {}),
            "metrics": {"queue": snap["queue"],
                        "batcher": {k: v for k, v in b.items()
                                    if k != "occupancy"}},
            "pass": not fail,
        }
        with open(args.json, "w") as fh:
            json.dump(artifact, fh, indent=2, sort_keys=True)
        print(f"[servebench] wrote {args.json}", file=sys.stderr)

    if fail:
        for f in fail:
            print(f"[servebench] FAIL: {f}", file=sys.stderr)
        return 1
    print("[servebench] PASS", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
