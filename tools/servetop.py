"""servetop: a live operator console over one or many serve replicas.

`top` for the polishing fleet: polls every replica endpoint (the same
spellings the fleet aggregator takes — unix socket / host:port RPC /
http:// metrics base, default RACON_TPU_FLEET_ENDPOINTS), merges the
scrapes through obs/fleet.py, and redraws one screen per poll:

  - the FLEET line: queue depth vs capacity, in-flight jobs, lifetime
    completed/failed, SLO hits/misses with the live burn-rate (fast/
    slow window multiples of budget, [FIRING] when the dual-window
    alert is up), device iterations with the fleet-wide rate;
  - one ROW PER REPLICA: reachability, draining flag, queue/in-flight,
    iteration rate since the last poll, busy worker lanes, compiles
    (compile activity after warmup is the "something is recompiling"
    smell), scrape round-trip;
  - PER-TENANT rows: live queued jobs and accrued DRR credit (the
    fairness dial) from the labeled scrape series;
  - AUTOTUNER activity: winner-table consult counts by (engine,
    decision, dtype) — which kernel plane the fleet is actually
    dispatching;
  - a ROUTER suffix on the fleet line (rendered only when a polled
    endpoint is the shard-aware router, serve/router.py): routable vs
    configured replicas behind it, the draining count mid rolling
    restart, and outstanding requeued shards with [REQUEUED] while any
    lost shard is still waiting to finish on a survivor;
  - AUDIT rows (rendered only when a replica exposes the identity-audit
    families): one cell per replica with the sentinel's sampled/s rate,
    confirmed mismatches, online winner demotions and the worst lane
    health, plus [ALERT] while racon_tpu_audit_alert is up — the live
    silent-data-corruption view;
  - CACHE rows (rendered only when a replica armed the content-
    addressed window cache, serve/wincache.py): per replica the hit
    rate, resident bytes/entries, LRU evictions and quarantined
    entries — the dispatch-skip economics at a glance;
  - a ROUNDS suffix on the fleet line (rendered only once some replica
    ran a rounds=N job): iterative-rounds jobs in flight right now
    plus the lifetime completed-rounds/jobs counters;
  - a QOS suffix on the fleet line (rendered only once some replica
    arms preemption / abort margin / burst tokens or fires a QoS
    event): lifetime preemptions / doomed-aborts / cancels, with
    [PREEMPT n] while n jobs are parked by preemption right now;
  - an AUTOSCALE suffix on the fleet line (rendered only when a polled
    router armed the elastic-fleet loop, serve/autoscale.py): lifetime
    scale-up/scale-down counts, the last polled backlog pressure, and
    [SCALED +n] while n autoscaler-spawned replicas are alive.

On a TTY the screen redraws in place; on a pipe it degrades to one
summary line per poll (greppable, CI-friendly). `--once` polls once
and exits — the smoke-test shape.

    python tools/servetop.py --endpoints /tmp/a.sock,127.0.0.1:7788
    python tools/servetop.py --once   # RACON_TPU_FLEET_ENDPOINTS
"""

from __future__ import annotations

import argparse
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

G = "racon_tpu_serve_"


def _g(parsed, name, default=0.0):
    return (parsed.gauges if parsed else {}).get(name, default)


def _c(parsed, name, default=0.0):
    return (parsed.counters if parsed else {}).get(name, default)


def _series(parsed, name) -> dict:
    """{labels_dict_key_value: value} for one labeled family."""
    if parsed is None:
        return {}
    series = dict(parsed.gauge_series.get(name, {}))
    series.update(parsed.counter_series.get(name, {}))
    return series


def audit_cell(p, prev: dict, dt: float) -> dict | None:
    """One replica's identity-audit cell from the sentinel's scrape
    families, or None when the replica doesn't expose them (audit
    off)."""
    if p is None or "racon_tpu_audit_sampled_total" not in p.counters:
        return None
    sampled = _c(p, "racon_tpu_audit_sampled_total")
    prev_a = prev.get("audit") or {}
    rate = ((sampled - prev_a.get("sampled", sampled)) / dt
            if dt > 0 else 0.0)
    mism = sum(int(v) for _labels, v in
               p.counter_series.get("racon_tpu_audit_mismatches_total",
                                    {}).values())
    healths = [v for _labels, v in
               p.gauge_series.get("racon_tpu_lane_health",
                                  {}).values()]
    return {"sampled": int(sampled), "sampled_rate": rate,
            "mismatches": mism,
            "demotions": int(_c(p, "racon_tpu_audit_demotions_total")),
            "lane_health_min": min(healths) if healths else 1.0,
            "alert": bool(p.gauges.get("racon_tpu_audit_alert", 0))}


def cache_cell(p) -> dict | None:
    """One replica's window-cache cell from the wincache scrape
    families, or None when the replica doesn't expose them (cache
    unarmed — the families are armed-only, like the audit ones)."""
    if p is None or "racon_tpu_serve_wincache_bytes" not in p.gauges:
        return None
    ops = {labels.get("op"): v for labels, v in
           p.counter_series.get("racon_tpu_serve_wincache_ops_total",
                                {}).values()}
    hits = ops.get("hit", 0)
    lookups = hits + ops.get("miss", 0)
    return {"hit_pct": hits / lookups * 100.0 if lookups else 0.0,
            "hits": int(hits),
            "bytes": int(_g(p, "racon_tpu_serve_wincache_bytes")),
            "entries": int(_g(p, "racon_tpu_serve_wincache_entries")),
            "evictions": int(ops.get("eviction", 0)),
            "quarantined": int(ops.get("quarantined", 0))}


def replica_row(rs, prev: dict, dt: float) -> dict:
    """One replica's console row, with rates from the previous poll."""
    p = rs.parsed
    iters = _c(p, G + "batch_iterations_total")
    rate = ((iters - prev.get("iterations", iters)) / dt
            if dt > 0 else 0.0)
    lanes_busy = lanes_total = 0
    if p is not None:
        for name, v in p.gauges.items():
            if name.startswith(G + "lane_") and name.endswith("_busy"):
                lanes_total += 1
                lanes_busy += int(v)
        if not lanes_total:
            lanes_total = int(_g(p, G + "worker_lanes", 1))
    return {"endpoint": rs.endpoint, "ok": rs.ok,
            "draining": rs.draining, "error": rs.error,
            "queue": int(_g(p, G + "queue_depth")),
            "inflight": int(_g(p, G + "inflight")),
            "iterations": iters, "iter_rate": rate,
            "lanes_busy": lanes_busy, "lanes": lanes_total,
            "compiles": int(_c(p, G + "compiles_total")),
            "scrape_ms": rs.scrape_s * 1e3,
            "audit": audit_cell(p, prev, dt),
            "cache": cache_cell(p)}


def tenant_rows(snap) -> list[dict]:
    """Merged per-tenant queued/credit/device-seconds across the
    fleet (device_s from the prorated cost-accounting counter)."""
    tenants: dict[str, dict] = {}

    def _row(t: str) -> dict:
        return tenants.setdefault(
            t, {"queued": 0, "credit": 0.0, "device_s": 0.0})

    for name, key in ((G + "tenant_queue_depth", "queued"),
                      (G + "tenant_credit", "credit")):
        for labels, v in snap.gauge_series.get(name, {}).values():
            _row(labels.get("tenant", ""))[key] += v
    for labels, v in snap.counter_series.get(
            G + "tenant_device_seconds_total", {}).values():
        _row(labels.get("tenant", ""))["device_s"] += v
    return [dict(row, tenant=t or "<anon>")
            for t, row in sorted(tenants.items())]


def autotune_rows(snap) -> list[tuple[str, int]]:
    out = []
    for labels, v in snap.counter_series.get(
            "racon_tpu_sched_autotune_consults_total", {}).values():
        tag = "/".join(x for x in (labels.get("engine", "?"),
                                   labels.get("decision", "?"),
                                   labels.get("dtype", "")) if x)
        out.append((tag, int(v)))
    return sorted(out)


def fleet_line(snap, burn: dict, prev: dict, dt: float) -> str:
    iters = snap.counters.get(G + "batch_iterations_total", 0)
    rate = ((iters - prev.get("iterations", iters)) / dt
            if dt > 0 else 0.0)
    hit = int(snap.counters.get(G + "jobs_deadline_hit_total", 0))
    miss = int(snap.counters.get(G + "jobs_deadline_miss_total", 0))
    return (f"fleet  queue {int(snap.gauges.get(G + 'queue_depth', 0))}"
            f"/{int(snap.gauges.get(G + 'queue_capacity', 0))}"
            f"  inflight {int(snap.gauges.get(G + 'inflight', 0))}"
            f"  completed {int(snap.counters.get(G + 'jobs_completed_total', 0))}"
            f" ({int(snap.counters.get(G + 'jobs_failed_total', 0))} failed)"
            f"  slo {hit}+/{miss}-"
            f"  burn {burn.get('fast', 0):g}x/{burn.get('slow', 0):g}x"
            f"{' [FIRING]' if burn.get('firing') else ''}"
            f"  iters {int(iters)} ({rate:.1f}/s)"
            f"  compiles {int(snap.counters.get(G + 'compiles_total', 0))}"
            + _fleet_audit(snap) + _fleet_rounds(snap)
            + _fleet_preempt(snap) + _fleet_router(snap)
            + _fleet_autoscale(snap))


def _fleet_audit(snap) -> str:
    """Fleet-level audit suffix (empty when no replica audits): the
    federated mismatch total plus [AUDIT-ALERT] while any replica's
    racon_tpu_audit_alert gauge is up."""
    if "racon_tpu_audit_sampled_total" not in snap.counters:
        return ""
    mism = sum(int(v) for _labels, v in snap.counter_series.get(
        "racon_tpu_audit_mismatches_total", {}).values())
    return (f"  audit {mism} mism"
            + ("  [AUDIT-ALERT]"
               if snap.gauges.get("racon_tpu_audit_alert", 0) else ""))


def _fleet_rounds(snap) -> str:
    """Iterative-rounds suffix (empty until some replica ran a
    rounds=N job — the families are armed-only): rounds jobs in flight
    now, plus the lifetime completed-rounds / rounds-jobs counters."""
    if "racon_tpu_serve_rounds_inflight" not in snap.gauges:
        return ""
    inflight = int(snap.gauges.get("racon_tpu_serve_rounds_inflight",
                                   0))
    jobs = int(snap.counters.get("racon_tpu_serve_rounds_jobs_total",
                                 0))
    done = int(snap.counters.get(
        "racon_tpu_serve_rounds_completed_total", 0))
    return f"  rounds {inflight} infl ({done}r/{jobs}j)"


def _fleet_preempt(snap) -> str:
    """QoS suffix (empty until some replica arms preemption / abort
    margin / burst tokens or fires a QoS event — the families are
    armed-only): lifetime preemptions, doomed-aborts and cancels, plus
    [PREEMPT] while any job is parked right now."""
    if "racon_tpu_serve_preemptions_total" not in snap.counters:
        return ""
    pre = int(snap.counters.get("racon_tpu_serve_preemptions_total", 0))
    doomed = int(snap.counters.get(
        "racon_tpu_serve_aborted_doomed_total", 0))
    cancelled = int(snap.counters.get(
        "racon_tpu_serve_cancelled_total", 0))
    parked = int(snap.gauges.get("racon_tpu_serve_preempted_inflight",
                                 0))
    return (f"  qos {pre}p/{doomed}d/{cancelled}c"
            + (f"  [PREEMPT {parked}]" if parked else ""))


def _fleet_router(snap) -> str:
    """Router suffix (empty when no polled endpoint is a shard-aware
    router, serve/router.py): routable vs configured replica counts
    behind the router, the draining count mid rolling restart, and the
    outstanding requeued shards — [REQUEUED] while any shard lost to a
    dead replica is still waiting to finish on a survivor."""
    if "racon_tpu_router_replicas" not in snap.gauges:
        return ""
    total = int(snap.gauges.get("racon_tpu_router_replicas", 0))
    routable = int(snap.gauges.get(
        "racon_tpu_router_replicas_routable", 0))
    draining = int(snap.gauges.get(
        "racon_tpu_router_replicas_draining", 0))
    requeued = int(snap.gauges.get(
        "racon_tpu_router_requeued_outstanding", 0))
    return (f"  router {routable}/{total} routable"
            + (f" ({draining} drn)" if draining else "")
            + f"  requeued {requeued}"
            + ("  [REQUEUED]" if requeued else ""))


def _fleet_autoscale(snap) -> str:
    """Elastic-fleet suffix (empty unless a polled router armed the
    autoscaler, serve/autoscale.py — the families are armed-only):
    lifetime scale-ups/scale-downs, the last polled backlog pressure
    (queued+inflight jobs per routable replica), and [SCALED +n] while
    n autoscaler-owned replicas are alive right now."""
    if "racon_tpu_router_autoscale_spawned" not in snap.gauges:
        return ""
    ups = int(snap.counters.get(
        "racon_tpu_router_autoscale_scale_ups", 0))
    downs = int(snap.counters.get(
        "racon_tpu_router_autoscale_scale_downs", 0))
    spawned = int(snap.gauges.get(
        "racon_tpu_router_autoscale_spawned", 0))
    pressure = snap.gauges.get("racon_tpu_router_autoscale_pressure",
                               0.0)
    return (f"  autoscale {ups}u/{downs}d pressure {pressure:g}"
            + (f"  [SCALED +{spawned}]" if spawned else ""))


def render_screen(snap, burn: dict, rows: list[dict], prev: dict,
                  dt: float) -> str:
    up = sum(1 for r in snap.replicas if r.ok)
    lines = [f"racon-tpu servetop — {len(snap.replicas)} replica(s), "
             f"{up} up · {time.strftime('%H:%M:%S')} · poll "
             f"{snap.poll_s * 1e3:.0f}ms",
             fleet_line(snap, burn, prev, dt), ""]
    lines.append(f"{'replica':<36} {'up':>2} {'drn':>3} {'queue':>5} "
                 f"{'infl':>4} {'it/s':>6} {'lanes':>5} {'cmpl':>4} "
                 f"{'ms':>5}")
    for row in rows:
        if row["error"]:
            lines.append(f"{row['endpoint']:<36}  -  DOWN  "
                         f"{row['error']}")
            continue
        lines.append(
            f"{row['endpoint']:<36} {'y' if row['ok'] else 'n':>2} "
            f"{'y' if row['draining'] else '-':>3} "
            f"{row['queue']:>5} {row['inflight']:>4} "
            f"{row['iter_rate']:>6.1f} "
            f"{row['lanes_busy']}/{row['lanes']:<3} "
            f"{row['compiles']:>4} {row['scrape_ms']:>5.1f}")
    tenants = tenant_rows(snap)
    if tenants:
        lines.append("")
        lines.append(f"{'tenant':<20} {'queued':>6} {'credit':>8} "
                     f"{'dev-s':>8}")
        for t in tenants:
            lines.append(f"{t['tenant']:<20} {int(t['queued']):>6} "
                         f"{t['credit']:>8.2f} "
                         f"{t.get('device_s', 0.0):>8.2f}")
    tunes = autotune_rows(snap)
    if tunes:
        lines.append("")
        lines.append("autotune  " + "  ".join(
            f"{tag}={n}" for tag, n in tunes))
    cache_rows = [(r["endpoint"], r["cache"]) for r in rows
                  if r.get("cache")]
    if cache_rows:
        lines.append("")
        lines.append(f"{'wincache':<36} {'hit%':>6} {'MiB':>7} "
                     f"{'entr':>5} {'evict':>5} {'quar':>4}")
        for endpoint, c in cache_rows:
            lines.append(
                f"{endpoint:<36} {c['hit_pct']:>6.1f} "
                f"{c['bytes'] / (1 << 20):>7.2f} {c['entries']:>5} "
                f"{c['evictions']:>5} {c['quarantined']:>4}")
    audit_rows = [(r["endpoint"], r["audit"]) for r in rows
                  if r.get("audit")]
    if audit_rows:
        lines.append("")
        lines.append(f"{'audit':<36} {'smp/s':>6} {'mism':>5} "
                     f"{'demot':>5} {'laneh':>6}")
        for endpoint, a in audit_rows:
            lines.append(
                f"{endpoint:<36} {a['sampled_rate']:>6.1f} "
                f"{a['mismatches']:>5} {a['demotions']:>5} "
                f"{a['lane_health_min']:>6.2f}"
                + ("  [ALERT]" if a["alert"] else ""))
    return "\n".join(lines)


def render_line(snap, burn: dict, prev: dict, dt: float) -> str:
    """The one-line-per-poll pipe mode."""
    up = sum(1 for r in snap.replicas if r.ok)
    return (f"[servetop] up={up}/{len(snap.replicas)} "
            + fleet_line(snap, burn, prev, dt))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="live serve-fleet console (see module docstring)")
    ap.add_argument("--endpoints", default=None,
                    help="comma-separated replica endpoints (default: "
                         "RACON_TPU_FLEET_ENDPOINTS)")
    ap.add_argument("--interval", type=float, default=2.0,
                    help="poll interval seconds (default 2)")
    ap.add_argument("--timeout", type=float, default=2.0,
                    help="per-replica scrape timeout seconds")
    ap.add_argument("--once", action="store_true",
                    help="poll once, print, exit (0 = all replicas "
                         "healthy)")
    ap.add_argument("--no-tty", action="store_true",
                    help="force the one-line-per-poll pipe mode")
    args = ap.parse_args(argv)

    from racon_tpu.obs.fleet import FleetAggregator

    endpoints = ([e.strip() for e in args.endpoints.split(",")
                  if e.strip()] if args.endpoints else None)
    try:
        agg = FleetAggregator(endpoints, timeout_s=args.timeout)
    except ValueError as exc:
        print(f"[servetop] error: {exc}", file=sys.stderr)
        return 2

    tty = sys.stdout.isatty() and not args.no_tty and not args.once
    prev: dict = {}
    prev_rows: dict = {}
    t_prev = None
    try:
        while True:
            snap = agg.poll()
            now = time.monotonic()
            dt = (now - t_prev) if t_prev is not None else 0.0
            t_prev = now
            burn = agg.burn.state()
            rows = [replica_row(r, prev_rows.get(r.endpoint, {}), dt)
                    for r in snap.replicas]
            if tty:
                sys.stdout.write("\x1b[H\x1b[2J")
                print(render_screen(snap, burn, rows, prev, dt))
            elif args.once:
                print(render_screen(snap, burn, rows, prev, dt))
            else:
                print(render_line(snap, burn, prev, dt), flush=True)
            prev = {"iterations": snap.counters.get(
                G + "batch_iterations_total", 0)}
            prev_rows = {row["endpoint"]: row for row in rows}
            if args.once:
                return 0 if snap.healthy else 1
            time.sleep(args.interval)
    except KeyboardInterrupt:
        return 0


if __name__ == "__main__":
    sys.exit(main())
