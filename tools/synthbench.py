"""Synthetic scale benchmark: ONT-style polishing at arbitrary genome size.

The BASELINE.md north star is E. coli 30x ONT polishing throughput; the
packaged sample is only 48.5 kb. This tool simulates the same shape of
workload at any scale — a random genome, a noisy draft, long reads with
ONT-like errors, and PAF overlaps derived from the simulation's true
coordinates — then polishes it and reports wall-clock, windows/sec, and
polished identity vs the simulated truth.

    python tools/synthbench.py --genome-kb 200 --coverage 30 [-c 1]

Unlike bench.py (the driver's one-line contract on the reference sample),
this is an engineering tool for scale/perf work.
"""

from __future__ import annotations

import argparse
import gzip
import os
import random
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

ACGT = b"ACGT"


def mutate(rng, s, rate):
    out = bytearray()
    for c in s:
        r = rng.random()
        if r < rate / 3:
            continue
        if r < 2 * rate / 3:
            out.append(rng.choice(ACGT))
            out.append(c)
            continue
        if r < rate:
            out.append(rng.choice(ACGT))
            continue
        out.append(c)
    return bytes(out)


def mutate_fast(nrng, s, rate, with_offsets=False):
    """Vectorized mutate() twin (numpy RNG, different stream — only used
    under --fast-sim, never for the seed-pinned goldens): same error
    model, dels/ins/subs each at rate/3, insertions placed before the
    kept base like mutate(). `with_offsets` additionally returns the
    exact input→output coordinate maps — land[i] = output position of
    input base i itself (for a deleted base: where it would have been)
    and seg[i] = output start of base i's segment with seg[n] = total
    output length, so seg[e] is the exclusive output end of span
    [b, e). Callers use these to emit drift-free coordinates — at
    multi-Mb scale the global-length-ratio approximation drifts by
    hundreds of bases (indel-count fluctuation grows with length) and
    distorts every derived overlap."""
    import numpy as np

    arr = np.frombuffer(s, dtype=np.uint8).copy()
    n = len(arr)
    u = nrng.random(n)
    dele = u < rate / 3
    ins = (u >= rate / 3) & (u < 2 * rate / 3)
    sub = (u >= 2 * rate / 3) & (u < rate)
    bases = np.frombuffer(ACGT, dtype=np.uint8)
    arr[sub] = bases[nrng.integers(0, 4, int(sub.sum()))]
    out_len = np.where(dele, 0, np.where(ins, 2, 1))
    off = np.zeros(n, dtype=np.int64)
    np.cumsum(out_len[:-1], out=off[1:])
    total = int(off[-1] + out_len[-1]) if n else 0
    out = np.empty(total, dtype=np.uint8)
    keep = ~dele
    out[off[keep] + ins[keep]] = arr[keep]
    ins_keep = ins & keep
    out[off[ins_keep]] = bases[nrng.integers(0, 4, int(ins_keep.sum()))]
    if with_offsets:
        land = off + (ins & keep)
        seg = np.append(off, total)
        return out.tobytes(), land, seg
    return out.tobytes()


def simulate_fast(seed, genome_len, coverage, read_len, read_err,
                  draft_err):
    """Vectorized simulate() for multi-Mb genomes (numpy RNG stream;
    deterministic for a seed but NOT byte-compatible with simulate())."""
    import numpy as np

    nrng = np.random.default_rng(seed)
    bases = np.frombuffer(ACGT, dtype=np.uint8)
    truth = bases[nrng.integers(0, 4, genome_len)].tobytes()
    # exact truth→draft coordinate map: PAF coordinates must be the
    # draft positions where the read's truth span actually lands, not a
    # global-length-ratio guess (which drifts ±hundreds of bases at
    # multi-Mb scale and distorts every window layer derived from it)
    draft, t_land, t_seg = mutate_fast(nrng, truth, draft_err,
                                       with_offsets=True)

    comp = bytes.maketrans(b"ACGT", b"TGCA")
    reads, paf = [], []
    n_reads = genome_len * coverage // read_len
    starts = nrng.integers(0, max(1, genome_len - read_len // 2), n_reads)
    strands = nrng.random(n_reads) < 0.5
    for i in range(n_reads):
        start = int(starts[i])
        end = min(genome_len, start + read_len)
        fwd = mutate_fast(nrng, truth[start:end], read_err)
        read = fwd.translate(comp)[::-1] if strands[i] else fwd
        name = f"read{i}"
        t_begin = int(t_land[start])
        t_end = int(t_seg[end]) if end > start else t_begin
        reads.append((name, read))
        paf.append(f"{name}\t{len(read)}\t0\t{len(read)}\t"
                   f"{'-' if strands[i] else '+'}\tdraft\t{len(draft)}\t"
                   f"{t_begin}\t{t_end}\t{end - start}\t{end - start}\t60")
    return truth, draft, reads, paf


def simulate(rng, genome_len, coverage, read_len, read_err, draft_err):
    truth = bytes(rng.choice(ACGT) for _ in range(genome_len))
    draft = mutate(rng, truth, draft_err)

    reads, paf = [], []
    n_reads = genome_len * coverage // read_len
    scale = len(draft) / len(truth)
    for i in range(n_reads):
        start = rng.randrange(0, max(1, genome_len - read_len // 2))
        end = min(genome_len, start + read_len)
        fwd = mutate(rng, truth[start:end], read_err)
        strand = rng.random() < 0.5
        if strand:
            comp = bytes.maketrans(b"ACGT", b"TGCA")
            read = fwd.translate(comp)[::-1]
        else:
            read = fwd
        name = f"read{i}"
        t_begin = int(start * scale)
        t_end = min(len(draft), int(end * scale))
        reads.append((name, read))
        paf.append(f"{name}\t{len(read)}\t0\t{len(read)}\t"
                   f"{'-' if strand else '+'}\tdraft\t{len(draft)}\t"
                   f"{t_begin}\t{t_end}\t{end - start}\t{end - start}\t60")
    return truth, draft, reads, paf


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--genome-kb", type=int, default=200)
    ap.add_argument("--coverage", type=int, default=30)
    ap.add_argument("--read-len", type=int, default=8000)
    ap.add_argument("--read-err", type=float, default=0.12)
    ap.add_argument("--draft-err", type=float, default=0.10)
    ap.add_argument("-w", "--window-length", type=int, default=500)
    ap.add_argument("-t", "--threads", type=int, default=os.cpu_count() or 1)
    ap.add_argument("-c", "--tpupoa-batches", type=int, default=0)
    ap.add_argument("--tpualigner-batches", type=int, default=0)
    ap.add_argument("--adaptive-buckets", action="store_true",
                    help="arm the occupancy-aware batch scheduler "
                         "(adaptive shape ladders + sorted packing); "
                         "the occupancy report below A/Bs the win")
    ap.add_argument("--seed", type=int, default=42)
    ap.add_argument("--fast-sim", action="store_true",
                    help="vectorized simulator for multi-Mb genomes "
                         "(deterministic per seed, but a different RNG "
                         "stream than the default — goldens pin the "
                         "default)")
    ap.add_argument("--golden-out", default=None,
                    help="write the polished FASTA here (golden artifact; "
                         "deterministic for a given seed/params)")
    ap.add_argument("--json", metavar="PATH", default=None,
                    help="write a machine-readable artifact (mode "
                         "'synth': windows_per_s, phase seconds, "
                         "identity, per-bucket occupancy incl. the "
                         "dispatched kernel/dtype choice) — the shape "
                         "tools/perfgate.py gates with "
                         "--windows-per-s-min / --against")
    ap.add_argument("--trace", metavar="PATH", default=None,
                    help="record a Chrome trace (Perfetto) of the polish "
                         "to PATH and report trace-recording overhead vs "
                         "an untraced baseline run of the same workload "
                         "(target: < 2%%)")
    ap.add_argument("--flight", action="store_true",
                    help="A/B the always-on flight recorder "
                         "(obs/flight.py, the bounded ring the serve "
                         "layer installs) against an unrecorded "
                         "baseline run of the same workload "
                         "(target: < 2%% — the serve-mode overhead "
                         "budget)")
    ap.add_argument("--progress-journal", action="store_true",
                    help="A/B the serve-mode live-progress hook plus a "
                         "JSONL event journal write per progress event "
                         "(obs/journal.py) against an uninstrumented "
                         "baseline run of the same workload "
                         "(target: < 2%% — the serve-mode overhead "
                         "budget)")
    args = ap.parse_args(argv)

    from racon_tpu.core.polisher import create_polisher, PolisherType
    from racon_tpu.native import edit_distance

    rng = random.Random(args.seed)
    genome_len = args.genome_kb * 1000
    print(f"[synthbench] simulating {args.genome_kb} kb genome at "
          f"{args.coverage}x ...", file=sys.stderr)
    if args.fast_sim:
        truth, draft, reads, paf = simulate_fast(
            args.seed, genome_len, args.coverage, args.read_len,
            args.read_err, args.draft_err)
    else:
        truth, draft, reads, paf = simulate(rng, genome_len, args.coverage,
                                            args.read_len, args.read_err,
                                            args.draft_err)

    with tempfile.TemporaryDirectory() as d:
        reads_path = os.path.join(d, "reads.fasta.gz")
        with gzip.open(reads_path, "wb", compresslevel=1) as f:
            for name, read in reads:
                f.write(b">" + name.encode() + b"\n" + read + b"\n")
        paf_path = os.path.join(d, "ovl.paf.gz")
        with gzip.open(paf_path, "wb", compresslevel=1) as f:
            f.write(("\n".join(paf) + "\n").encode())
        draft_path = os.path.join(d, "draft.fasta.gz")
        with gzip.open(draft_path, "wb", compresslevel=1) as f:
            f.write(b">draft\n" + draft + b"\n")

        def run_polish(instrument=None):
            t0 = time.perf_counter()
            polisher = create_polisher(
                reads_path, paf_path, draft_path, PolisherType.kC,
                args.window_length, 10.0, 0.3, True, 5, -4, -8,
                num_threads=args.threads,
                tpu_poa_batches=args.tpupoa_batches,
                tpu_aligner_batches=args.tpualigner_batches,
                tpu_adaptive_buckets=args.adaptive_buckets or None)
            if instrument is not None:
                instrument(polisher)
            polisher.initialize()
            t1 = time.perf_counter()
            n_windows = len(polisher.windows)
            polished = polisher.polish()
            t2 = time.perf_counter()
            return polisher, polished, n_windows, t1 - t0, t2 - t1

        if args.trace:
            # overhead A/B on the SAME workload: a discarded warmup run
            # first, so one-time process-wide costs (XLA jit compiles,
            # compile telemetry, lazy imports) are paid before EITHER
            # measured run — a cold baseline vs warm traced comparison
            # would systematically understate the overhead — then the
            # untraced baseline, then the traced run (whose outputs the
            # identity metrics below use; all runs are deterministic)
            from racon_tpu.obs import trace as obs_trace

            run_polish()  # warmup, discarded
            _, _, _, _, base_polish_s = run_polish()
            # configure with NO path: polish()'s own end-of-run save is
            # then a no-op, so the timed region measures pure recording
            # overhead — serialization happens once, below, off-clock
            rec = obs_trace.configure(None)
            polisher, polished, n_windows, init_s, polish_s = run_polish()
            n_events = len(rec.events())
            rec.save(os.path.abspath(args.trace))
            obs_trace.reset()
            print(f"[synthbench] trace written to {args.trace}",
                  file=sys.stderr)
            overhead = ((polish_s - base_polish_s) / base_polish_s * 100
                        if base_polish_s > 0 else 0.0)
            print(f"[synthbench] trace overhead: {overhead:+.2f}% "
                  f"(baseline {base_polish_s:.2f}s, traced "
                  f"{polish_s:.2f}s, {n_events} events) "
                  f"[{'OK' if overhead < 2.0 else 'OVER'} 2% target]",
                  file=sys.stderr)
        elif args.flight:
            # same A/B discipline as --trace (warmup discarded, then
            # baseline, then recorded), but with the serve layer's
            # bounded FlightRecorder installed — the number that backs
            # the "always-on costs <2%" claim in README "Serving"
            from racon_tpu.obs import flight as obs_flight
            from racon_tpu.obs import trace as obs_trace

            run_polish()  # warmup, discarded
            _, _, _, _, base_polish_s = run_polish()
            rec = obs_trace.install(obs_flight.FlightRecorder())
            polisher, polished, n_windows, init_s, polish_s = run_polish()
            n_events = len(rec.events())
            obs_trace.reset()
            overhead = ((polish_s - base_polish_s) / base_polish_s * 100
                        if base_polish_s > 0 else 0.0)
            print(f"[synthbench] flight-recorder overhead: "
                  f"{overhead:+.2f}% (baseline {base_polish_s:.2f}s, "
                  f"recorded {polish_s:.2f}s, {n_events} ring events) "
                  f"[{'OK' if overhead < 2.0 else 'OVER'} 2% target]",
                  file=sys.stderr)
        elif args.progress_journal:
            # same A/B discipline as --trace / --flight, but with the
            # serve-mode progress hook armed AND every progress event
            # journaled — the number behind the "<2% for
            # progress+journal enabled" serve claim (README
            # "End-to-end tracing & progress")
            from racon_tpu.obs.journal import Journal

            run_polish()  # warmup, discarded
            _, _, _, _, base_polish_s = run_polish()
            journal = Journal(os.path.join(d, "journal.jsonl"))
            n_events = [0]

            def hook(ev, _j=journal, _n=n_events):
                _n[0] += 1
                _j.record("progress", job="synth", **ev)

            polisher, polished, n_windows, init_s, polish_s = run_polish(
                instrument=lambda p: setattr(p, "progress_hook", hook))
            journal.close()
            overhead = ((polish_s - base_polish_s) / base_polish_s * 100
                        if base_polish_s > 0 else 0.0)
            print(f"[synthbench] progress+journal overhead: "
                  f"{overhead:+.2f}% (baseline {base_polish_s:.2f}s, "
                  f"instrumented {polish_s:.2f}s, {n_events[0]} events "
                  f"journaled) "
                  f"[{'OK' if overhead < 2.0 else 'OVER'} 2% target]",
                  file=sys.stderr)
        else:
            polisher, polished, n_windows, init_s, polish_s = run_polish()
        # occupancy report: the per-bucket padding-waste metric the
        # adaptive scheduler moves (see README "Batch scheduling &
        # occupancy"); printed per bucket so a ladder change is
        # attributable, not just a single blended number
        for engine, e in polisher.occupancy_stats.items():
            if not e.get("buckets"):
                continue
            print(f"[synthbench] {engine} occupancy "
                  f"{e['occupancy_pct']:.1f}% (adaptive="
                  f"{'on' if polisher.scheduler.adaptive else 'off'})",
                  file=sys.stderr)
            for bucket, b in e["buckets"].items():
                plan = ""
                if "kernel" in b or "dtype" in b:
                    plan = (f", kernel {b.get('kernel', '?')}"
                            f"/{b.get('dtype', '?')}")
                print(f"[synthbench]   bucket {bucket}: {b['jobs']} jobs "
                      f"/ {b['batches']} batches, occupancy "
                      f"{b['occupancy_pct']:.1f}%{plan}", file=sys.stderr)

    if args.golden_out:
        with open(args.golden_out, "wb") as fh:
            for seq in polished:
                fh.write(b">" + seq.name.encode() + b"\n" + seq.data + b"\n")
        print(f"[synthbench] wrote golden {args.golden_out}", file=sys.stderr)

    # throughput first: the identity metric below costs O(genome^2/64)
    # Myers time at multi-Mb scale, and the perf number must survive a
    # wall-cap hitting mid-metric
    print(f"[synthbench] init {init_s:.1f}s  polish {polish_s:.1f}s  "
          f"({n_windows} windows, {n_windows / polish_s:.1f} windows/s)",
          file=sys.stderr)
    d_draft = edit_distance(draft, truth)
    d_pol = edit_distance(polished[0].data, truth)
    print(f"[synthbench] draft error {d_draft / genome_len * 100:.2f}%  "
          f"polished error {d_pol / genome_len * 100:.2f}%  "
          f"(identity {100 - d_pol / genome_len * 100:.3f}%)",
          file=sys.stderr)
    if args.json:
        import json

        artifact = {
            "mode": "synth",
            "synth": {
                "windows_per_s": round(n_windows / polish_s, 3)
                if polish_s > 0 else 0.0,
                "windows": n_windows,
                "init_s": round(init_s, 3),
                "polish_s": round(polish_s, 3),
                "identity_pct": round(100 - d_pol / genome_len * 100, 4),
                "genome_kb": args.genome_kb,
                "coverage": args.coverage,
                "seed": args.seed,
            },
            # per-bucket occupancy INCLUDING the dispatched kernel/dtype
            # choice — the autotuner's decision made visible per run
            "occupancy": polisher.occupancy_stats,
        }
        with open(args.json, "w") as fh:
            json.dump(artifact, fh, indent=1, sort_keys=True)
        print(f"[synthbench] wrote artifact {args.json}", file=sys.stderr)
    try:
        import resource

        rss_kb = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
        print(f"[synthbench] peak host RSS {rss_kb / 1024:.0f} MiB",
              file=sys.stderr)
    except Exception:
        pass
    return 0


if __name__ == "__main__":
    sys.exit(main())
