"""Synthetic scale benchmark: ONT-style polishing at arbitrary genome size.

The BASELINE.md north star is E. coli 30x ONT polishing throughput; the
packaged sample is only 48.5 kb. This tool simulates the same shape of
workload at any scale — a random genome, a noisy draft, long reads with
ONT-like errors, and PAF overlaps derived from the simulation's true
coordinates — then polishes it and reports wall-clock, windows/sec, and
polished identity vs the simulated truth.

    python tools/synthbench.py --genome-kb 200 --coverage 30 [-c 1]

Unlike bench.py (the driver's one-line contract on the reference sample),
this is an engineering tool for scale/perf work.
"""

from __future__ import annotations

import argparse
import gzip
import os
import random
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

ACGT = b"ACGT"


def mutate(rng, s, rate):
    out = bytearray()
    for c in s:
        r = rng.random()
        if r < rate / 3:
            continue
        if r < 2 * rate / 3:
            out.append(rng.choice(ACGT))
            out.append(c)
            continue
        if r < rate:
            out.append(rng.choice(ACGT))
            continue
        out.append(c)
    return bytes(out)


def mutate_fast(nrng, s, rate, with_offsets=False):
    """Vectorized mutate() twin (numpy RNG, different stream — only used
    under --fast-sim, never for the seed-pinned goldens): same error
    model, dels/ins/subs each at rate/3, insertions placed before the
    kept base like mutate(). `with_offsets` additionally returns the
    exact input→output coordinate maps — land[i] = output position of
    input base i itself (for a deleted base: where it would have been)
    and seg[i] = output start of base i's segment with seg[n] = total
    output length, so seg[e] is the exclusive output end of span
    [b, e). Callers use these to emit drift-free coordinates — at
    multi-Mb scale the global-length-ratio approximation drifts by
    hundreds of bases (indel-count fluctuation grows with length) and
    distorts every derived overlap."""
    import numpy as np

    arr = np.frombuffer(s, dtype=np.uint8).copy()
    n = len(arr)
    u = nrng.random(n)
    dele = u < rate / 3
    ins = (u >= rate / 3) & (u < 2 * rate / 3)
    sub = (u >= 2 * rate / 3) & (u < rate)
    bases = np.frombuffer(ACGT, dtype=np.uint8)
    arr[sub] = bases[nrng.integers(0, 4, int(sub.sum()))]
    out_len = np.where(dele, 0, np.where(ins, 2, 1))
    off = np.zeros(n, dtype=np.int64)
    np.cumsum(out_len[:-1], out=off[1:])
    total = int(off[-1] + out_len[-1]) if n else 0
    out = np.empty(total, dtype=np.uint8)
    keep = ~dele
    out[off[keep] + ins[keep]] = arr[keep]
    ins_keep = ins & keep
    out[off[ins_keep]] = bases[nrng.integers(0, 4, int(ins_keep.sum()))]
    if with_offsets:
        land = off + (ins & keep)
        seg = np.append(off, total)
        return out.tobytes(), land, seg
    return out.tobytes()


def simulate_fast(seed, genome_len, coverage, read_len, read_err,
                  draft_err):
    """Vectorized simulate() for multi-Mb genomes (numpy RNG stream;
    deterministic for a seed but NOT byte-compatible with simulate())."""
    import numpy as np

    nrng = np.random.default_rng(seed)
    bases = np.frombuffer(ACGT, dtype=np.uint8)
    truth = bases[nrng.integers(0, 4, genome_len)].tobytes()
    # exact truth→draft coordinate map: PAF coordinates must be the
    # draft positions where the read's truth span actually lands, not a
    # global-length-ratio guess (which drifts ±hundreds of bases at
    # multi-Mb scale and distorts every window layer derived from it)
    draft, t_land, t_seg = mutate_fast(nrng, truth, draft_err,
                                       with_offsets=True)

    comp = bytes.maketrans(b"ACGT", b"TGCA")
    reads, paf = [], []
    n_reads = genome_len * coverage // read_len
    starts = nrng.integers(0, max(1, genome_len - read_len // 2), n_reads)
    strands = nrng.random(n_reads) < 0.5
    for i in range(n_reads):
        start = int(starts[i])
        end = min(genome_len, start + read_len)
        fwd = mutate_fast(nrng, truth[start:end], read_err)
        read = fwd.translate(comp)[::-1] if strands[i] else fwd
        name = f"read{i}"
        t_begin = int(t_land[start])
        t_end = int(t_seg[end]) if end > start else t_begin
        reads.append((name, read))
        paf.append(f"{name}\t{len(read)}\t0\t{len(read)}\t"
                   f"{'-' if strands[i] else '+'}\tdraft\t{len(draft)}\t"
                   f"{t_begin}\t{t_end}\t{end - start}\t{end - start}\t60")
    return truth, draft, reads, paf


def simulate(rng, genome_len, coverage, read_len, read_err, draft_err):
    truth = bytes(rng.choice(ACGT) for _ in range(genome_len))
    draft = mutate(rng, truth, draft_err)

    reads, paf = [], []
    n_reads = genome_len * coverage // read_len
    scale = len(draft) / len(truth)
    for i in range(n_reads):
        start = rng.randrange(0, max(1, genome_len - read_len // 2))
        end = min(genome_len, start + read_len)
        fwd = mutate(rng, truth[start:end], read_err)
        strand = rng.random() < 0.5
        if strand:
            comp = bytes.maketrans(b"ACGT", b"TGCA")
            read = fwd.translate(comp)[::-1]
        else:
            read = fwd
        name = f"read{i}"
        t_begin = int(start * scale)
        t_end = min(len(draft), int(end * scale))
        reads.append((name, read))
        paf.append(f"{name}\t{len(read)}\t0\t{len(read)}\t"
                   f"{'-' if strand else '+'}\tdraft\t{len(draft)}\t"
                   f"{t_begin}\t{t_end}\t{end - start}\t{end - start}\t60")
    return truth, draft, reads, paf


def _scale_child_env(repo: str, n_devices: int) -> dict:
    """A scrubbed environment pinning the child to a CPU mesh of
    `n_devices` virtual devices (the __graft_entry__ dryrun discipline:
    no axon shim on the path, platform forced before jax init)."""
    env = dict(os.environ)
    keep = [p for p in env.get("PYTHONPATH", "").split(os.pathsep)
            if p and "axon_site" not in p and p != repo]
    env["PYTHONPATH"] = os.pathsep.join([repo] + keep)
    env["JAX_PLATFORMS"] = "cpu"
    flags = [f for f in env.get("XLA_FLAGS", "").split()
             if "xla_force_host_platform_device_count" not in f]
    flags.append(f"--xla_force_host_platform_device_count={n_devices}")
    env["XLA_FLAGS"] = " ".join(flags)
    env["RACON_TPU_MAX_DEVICES"] = str(n_devices)
    env.setdefault("JAX_COMPILATION_CACHE_DIR",
                   "/tmp/racon_tpu_jax_cache")
    return env


def _scale_point(n_devices: int, doc: dict, sha: str) -> dict:
    """One scale-curve point from a child artifact: throughput plus the
    mesh-waste view aggregated across every device engine's buckets —
    per-shard useful-cell balance (max/min; 1.0 = perfectly even) and
    the padded-cell fraction vs the full-mesh round_batch baseline (the
    sub-mesh tail dispatch win)."""
    from racon_tpu.sched.telemetry import accumulate_cells

    shards: list[int] = []
    useful = total = fm_cells = fm_useful = 0
    for engine in (doc.get("occupancy") or {}).values():
        # the engine-level raw sums OccupancyStats.snapshot() publishes
        # — summed across engines here (fractions cannot be combined,
        # raw cells can)
        accumulate_cells(shards, engine.get("shard_useful", ()))
        useful += engine.get("useful_cells", 0)
        total += engine.get("total_cells", 0)
        fm_cells += engine.get("full_mesh_cells", 0)
        fm_useful += engine.get("full_mesh_useful", 0)
    synth = doc.get("synth") or {}
    point = {"n_devices": n_devices,
             "windows_per_s": synth.get("windows_per_s"),
             "windows": synth.get("windows"),
             "polish_s": synth.get("polish_s"),
             "golden_sha": sha}
    if shards:
        point["shard_useful"] = shards
        if min(shards) > 0:
            point["shard_balance"] = round(max(shards) / min(shards), 4)
    if total:
        point["padded_frac"] = round((total - useful) / total, 6)
    if fm_cells:
        point["padded_frac_full_mesh"] = round(
            (fm_cells - fm_useful) / fm_cells, 6)
    return point


def run_scale_curve(args) -> int:
    """--scale-curve N1,N2,...: re-run the SAME workload once per mesh
    size (subprocess per point — the virtual device count must be
    pinned before jax initializes), assert the polished FASTA is
    byte-identical at every size, and emit a `scale` block in the
    --json artifact: windows/s, per-shard useful-cell balance, and the
    padded-cell fraction vs the full-mesh-rounding baseline per point —
    the numbers tools/perfgate.py gates mesh regressions on."""
    import hashlib
    import json
    import subprocess

    sizes = sorted({int(s) for s in args.scale_curve.split(",")
                    if s.strip()})
    if not sizes or min(sizes) < 1:
        print("[synthbench] --scale-curve wants positive device counts",
              file=sys.stderr)
        return 2
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    me = os.path.abspath(__file__)
    curve, shas = [], []
    with tempfile.TemporaryDirectory(prefix="racon_scale_") as d:
        for n in sizes:
            child_json = os.path.join(d, f"scale_{n}.json")
            golden = os.path.join(d, f"golden_{n}.fasta")
            cmd = [sys.executable, me,
                   "--genome-kb", str(args.genome_kb),
                   "--coverage", str(args.coverage),
                   "--read-len", str(args.read_len),
                   "--read-err", str(args.read_err),
                   "--draft-err", str(args.draft_err),
                   "-w", str(args.window_length),
                   "-t", str(args.threads),
                   "-c", str(args.tpupoa_batches),
                   "--tpualigner-batches", str(args.tpualigner_batches),
                   "--seed", str(args.seed),
                   "--json", child_json, "--golden-out", golden]
            if args.adaptive_buckets:
                cmd.append("--adaptive-buckets")
            if args.fast_sim:
                cmd.append("--fast-sim")
            print(f"[synthbench] scale point: {n} device(s) ...",
                  file=sys.stderr)
            proc = subprocess.run(cmd, env=_scale_child_env(repo, n),
                                  capture_output=True, text=True,
                                  timeout=3600)
            if proc.returncode != 0:
                sys.stderr.write(proc.stderr[-4000:])
                print(f"[synthbench] scale point {n} FAILED "
                      f"(rc {proc.returncode})", file=sys.stderr)
                return 1
            with open(child_json) as fh:
                doc = json.load(fh)
            with open(golden, "rb") as fh:
                sha = hashlib.sha256(fh.read()).hexdigest()
            shas.append(sha)
            point = _scale_point(n, doc, sha)
            curve.append(point)
            print(f"[synthbench]   {n} device(s): "
                  f"{point['windows_per_s']} windows/s, shard balance "
                  f"{point.get('shard_balance', 'n/a')}, padded "
                  f"{point.get('padded_frac', 'n/a')} (full-mesh "
                  f"baseline {point.get('padded_frac_full_mesh', 'n/a')})"
                  f", sha {sha[:12]}", file=sys.stderr)
    identical = len(set(shas)) == 1
    print(f"[synthbench] scale curve: polished FASTA "
          f"{'byte-identical' if identical else 'DIVERGED'} across mesh "
          f"sizes {sizes}", file=sys.stderr)
    if args.json:
        head = curve[-1]
        artifact = {
            "mode": "synth",
            "synth": {"windows_per_s": head["windows_per_s"],
                      "windows": head["windows"],
                      "polish_s": head["polish_s"],
                      "genome_kb": args.genome_kb,
                      "coverage": args.coverage,
                      "seed": args.seed},
            "scale": {"curve": curve, "identical": identical},
            # describes the headline (largest-mesh) CHILD, not this
            # orchestrator process — the one artifact whose mesh block
            # cannot come from the shared mesh_info() helper
            "mesh": {"n_devices": head["n_devices"],
                     "worker_lanes": 1,
                     "max_devices_env": str(head["n_devices"])},
        }
        with open(args.json, "w") as fh:
            json.dump(artifact, fh, indent=1, sort_keys=True)
        print(f"[synthbench] wrote artifact {args.json}",
              file=sys.stderr)
    return 0 if identical else 1


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--genome-kb", type=int, default=200)
    ap.add_argument("--coverage", type=int, default=30)
    ap.add_argument("--read-len", type=int, default=8000)
    ap.add_argument("--read-err", type=float, default=0.12)
    ap.add_argument("--draft-err", type=float, default=0.10)
    ap.add_argument("-w", "--window-length", type=int, default=500)
    ap.add_argument("-t", "--threads", type=int, default=os.cpu_count() or 1)
    ap.add_argument("-c", "--tpupoa-batches", type=int, default=0)
    ap.add_argument("--tpualigner-batches", type=int, default=0)
    ap.add_argument("--engine", choices=("session", "fused"),
                    default=None,
                    help="device consensus engine (with -c > 0); "
                         "default session — the fused engine is the "
                         "one the RACON_TPU_FUSED single-launch "
                         "program applies to")
    ap.add_argument("--dispatch-overhead", action="store_true",
                    help="A/B the fused single-launch dispatch "
                         "(RACON_TPU_FUSED=1) against the split "
                         "chained path (=0) on the same workload: "
                         "windows/s, measured host overhead (host_s = "
                         "polish wall - device-stage seconds) and "
                         "launch counts per mode, byte-identity "
                         "asserted; implies --engine fused and "
                         "requires -c > 0")
    ap.add_argument("--adaptive-buckets", action="store_true",
                    help="arm the occupancy-aware batch scheduler "
                         "(adaptive shape ladders + sorted packing); "
                         "the occupancy report below A/Bs the win")
    ap.add_argument("--seed", type=int, default=42)
    ap.add_argument("--scale-curve", default=None, metavar="N1,N2,...",
                    help="mesh-scaling sweep: re-run this workload once "
                         "per virtual-CPU mesh size (e.g. '1,2,4,8'), "
                         "assert byte-identical polished FASTA across "
                         "sizes, and record windows/s + per-shard "
                         "useful-cell balance + padded-cell fraction "
                         "vs the full-mesh-rounding baseline per point "
                         "in the --json artifact (gated by "
                         "tools/perfgate.py --scale-balance-max)")
    ap.add_argument("--fast-sim", action="store_true",
                    help="vectorized simulator for multi-Mb genomes "
                         "(deterministic per seed, but a different RNG "
                         "stream than the default — goldens pin the "
                         "default)")
    ap.add_argument("--golden-out", default=None,
                    help="write the polished FASTA here (golden artifact; "
                         "deterministic for a given seed/params)")
    ap.add_argument("--json", metavar="PATH", default=None,
                    help="write a machine-readable artifact (mode "
                         "'synth': windows_per_s, phase seconds, "
                         "identity, per-bucket occupancy incl. the "
                         "dispatched kernel/dtype choice) — the shape "
                         "tools/perfgate.py gates with "
                         "--windows-per-s-min / --against")
    ap.add_argument("--trace", metavar="PATH", default=None,
                    help="record a Chrome trace (Perfetto) of the polish "
                         "to PATH and report trace-recording overhead vs "
                         "an untraced baseline run of the same workload "
                         "(target: < 2%%)")
    ap.add_argument("--flight", action="store_true",
                    help="A/B the always-on flight recorder "
                         "(obs/flight.py, the bounded ring the serve "
                         "layer installs) against an unrecorded "
                         "baseline run of the same workload "
                         "(target: < 2%% — the serve-mode overhead "
                         "budget)")
    ap.add_argument("--progress-journal", action="store_true",
                    help="A/B the serve-mode live-progress hook plus a "
                         "JSONL event journal write per progress event "
                         "(obs/journal.py) against an uninstrumented "
                         "baseline run of the same workload "
                         "(target: < 2%% — the serve-mode overhead "
                         "budget)")
    args = ap.parse_args(argv)

    if args.dispatch_overhead:
        if args.tpupoa_batches <= 0:
            print("[synthbench] --dispatch-overhead needs device "
                  "consensus (-c > 0)", file=sys.stderr)
            return 2
        args.engine = "fused"

    if args.scale_curve:
        return run_scale_curve(args)

    from racon_tpu.core.polisher import create_polisher, PolisherType
    from racon_tpu.native import edit_distance

    rng = random.Random(args.seed)
    genome_len = args.genome_kb * 1000
    print(f"[synthbench] simulating {args.genome_kb} kb genome at "
          f"{args.coverage}x ...", file=sys.stderr)
    if args.fast_sim:
        truth, draft, reads, paf = simulate_fast(
            args.seed, genome_len, args.coverage, args.read_len,
            args.read_err, args.draft_err)
    else:
        truth, draft, reads, paf = simulate(rng, genome_len, args.coverage,
                                            args.read_len, args.read_err,
                                            args.draft_err)

    with tempfile.TemporaryDirectory() as d:
        reads_path = os.path.join(d, "reads.fasta.gz")
        with gzip.open(reads_path, "wb", compresslevel=1) as f:
            for name, read in reads:
                f.write(b">" + name.encode() + b"\n" + read + b"\n")
        paf_path = os.path.join(d, "ovl.paf.gz")
        with gzip.open(paf_path, "wb", compresslevel=1) as f:
            f.write(("\n".join(paf) + "\n").encode())
        draft_path = os.path.join(d, "draft.fasta.gz")
        with gzip.open(draft_path, "wb", compresslevel=1) as f:
            f.write(b">draft\n" + draft + b"\n")

        dispatch_ab = None
        fused_mode_label = None  # the mode the MEASURED run dispatched

        def run_polish(instrument=None):
            t0 = time.perf_counter()
            polisher = create_polisher(
                reads_path, paf_path, draft_path, PolisherType.kC,
                args.window_length, 10.0, 0.3, True, 5, -4, -8,
                num_threads=args.threads,
                tpu_poa_batches=args.tpupoa_batches,
                tpu_aligner_batches=args.tpualigner_batches,
                tpu_engine=args.engine,
                tpu_adaptive_buckets=args.adaptive_buckets or None)
            if instrument is not None:
                instrument(polisher)
            polisher.initialize()
            t1 = time.perf_counter()
            n_windows = len(polisher.windows)
            polished = polisher.polish()
            t2 = time.perf_counter()
            return polisher, polished, n_windows, t1 - t0, t2 - t1

        if args.trace:
            # overhead A/B on the SAME workload: a discarded warmup run
            # first, so one-time process-wide costs (XLA jit compiles,
            # compile telemetry, lazy imports) are paid before EITHER
            # measured run — a cold baseline vs warm traced comparison
            # would systematically understate the overhead — then the
            # untraced baseline, then the traced run (whose outputs the
            # identity metrics below use; all runs are deterministic)
            from racon_tpu.obs import trace as obs_trace

            run_polish()  # warmup, discarded
            _, _, _, _, base_polish_s = run_polish()
            # configure with NO path: polish()'s own end-of-run save is
            # then a no-op, so the timed region measures pure recording
            # overhead — serialization happens once, below, off-clock
            rec = obs_trace.configure(None)
            polisher, polished, n_windows, init_s, polish_s = run_polish()
            n_events = len(rec.events())
            rec.save(os.path.abspath(args.trace))
            obs_trace.reset()
            print(f"[synthbench] trace written to {args.trace}",
                  file=sys.stderr)
            overhead = ((polish_s - base_polish_s) / base_polish_s * 100
                        if base_polish_s > 0 else 0.0)
            print(f"[synthbench] trace overhead: {overhead:+.2f}% "
                  f"(baseline {base_polish_s:.2f}s, traced "
                  f"{polish_s:.2f}s, {n_events} events) "
                  f"[{'OK' if overhead < 2.0 else 'OVER'} 2% target]",
                  file=sys.stderr)
        elif args.flight:
            # same A/B discipline as --trace (warmup discarded, then
            # baseline, then recorded), but with the serve layer's
            # bounded FlightRecorder installed — the number that backs
            # the "always-on costs <2%" claim in README "Serving"
            from racon_tpu.obs import flight as obs_flight
            from racon_tpu.obs import trace as obs_trace

            run_polish()  # warmup, discarded
            _, _, _, _, base_polish_s = run_polish()
            rec = obs_trace.install(obs_flight.FlightRecorder())
            polisher, polished, n_windows, init_s, polish_s = run_polish()
            n_events = len(rec.events())
            obs_trace.reset()
            overhead = ((polish_s - base_polish_s) / base_polish_s * 100
                        if base_polish_s > 0 else 0.0)
            print(f"[synthbench] flight-recorder overhead: "
                  f"{overhead:+.2f}% (baseline {base_polish_s:.2f}s, "
                  f"recorded {polish_s:.2f}s, {n_events} ring events) "
                  f"[{'OK' if overhead < 2.0 else 'OVER'} 2% target]",
                  file=sys.stderr)
        elif args.progress_journal:
            # same A/B discipline as --trace / --flight, but with the
            # serve-mode progress hook armed AND every progress event
            # journaled — the number behind the "<2% for
            # progress+journal enabled" serve claim (README
            # "End-to-end tracing & progress")
            from racon_tpu.obs.journal import Journal

            run_polish()  # warmup, discarded
            _, _, _, _, base_polish_s = run_polish()
            journal = Journal(os.path.join(d, "journal.jsonl"))
            n_events = [0]

            def hook(ev, _j=journal, _n=n_events):
                _n[0] += 1
                _j.record("progress", job="synth", **ev)

            polisher, polished, n_windows, init_s, polish_s = run_polish(
                instrument=lambda p: setattr(p, "progress_hook", hook))
            journal.close()
            overhead = ((polish_s - base_polish_s) / base_polish_s * 100
                        if base_polish_s > 0 else 0.0)
            print(f"[synthbench] progress+journal overhead: "
                  f"{overhead:+.2f}% (baseline {base_polish_s:.2f}s, "
                  f"instrumented {polish_s:.2f}s, {n_events[0]} events "
                  f"journaled) "
                  f"[{'OK' if overhead < 2.0 else 'OVER'} 2% target]",
                  file=sys.stderr)
        elif args.dispatch_overhead:
            # A/B the two dispatch modes on the SAME workload (the
            # --trace discipline: a discarded warmup run per mode
            # absorbs that mode's compiles before its measured run).
            # Byte-identity across modes is asserted — the fused
            # program may move every perf number, never a byte.
            saved_mode = os.environ.get("RACON_TPU_FUSED")
            dispatch_ab = {}
            try:
                for mode, label in (("0", "split"), ("1", "fused")):
                    os.environ["RACON_TPU_FUSED"] = mode
                    run_polish()  # warmup, discarded
                    polisher, polished, n_windows, init_s, polish_s = \
                        run_polish()
                    ss = polisher.stage_stats
                    dispatch_ab[label] = {
                        "windows_per_s": round(n_windows / polish_s, 3)
                        if polish_s > 0 else 0.0,
                        "polish_s": round(polish_s, 3),
                        "device_s": round(ss["device_s"], 3),
                        "host_s": round(
                            max(0.0, polish_s - ss["device_s"]), 3),
                        "launches": ss["launches"],
                        "chunks": ss["chunks"],
                        "_fasta": [(s.name, s.data) for s in polished],
                    }
            finally:
                if saved_mode is None:
                    os.environ.pop("RACON_TPU_FUSED", None)
                else:
                    os.environ["RACON_TPU_FUSED"] = saved_mode
            fused_mode_label = "1"  # the headline run dispatched fused
            dispatch_ab["identical"] = (
                dispatch_ab["split"].pop("_fasta")
                == dispatch_ab["fused"].pop("_fasta"))
            sp, fu = dispatch_ab["split"], dispatch_ab["fused"]
            print(f"[synthbench] dispatch A/B: split "
                  f"{sp['windows_per_s']} w/s (host {sp['host_s']}s, "
                  f"{sp['launches']} launches) vs fused "
                  f"{fu['windows_per_s']} w/s (host {fu['host_s']}s, "
                  f"{fu['launches']} launches), FASTA "
                  f"{'identical' if dispatch_ab['identical'] else 'DIVERGED'}",
                  file=sys.stderr)
        else:
            polisher, polished, n_windows, init_s, polish_s = run_polish()
        # occupancy report: the per-bucket padding-waste metric the
        # adaptive scheduler moves (see README "Batch scheduling &
        # occupancy"); printed per bucket so a ladder change is
        # attributable, not just a single blended number
        for engine, e in polisher.occupancy_stats.items():
            if not e.get("buckets"):
                continue
            print(f"[synthbench] {engine} occupancy "
                  f"{e['occupancy_pct']:.1f}% (adaptive="
                  f"{'on' if polisher.scheduler.adaptive else 'off'})",
                  file=sys.stderr)
            for bucket, b in e["buckets"].items():
                plan = ""
                if "kernel" in b or "dtype" in b:
                    plan = (f", kernel {b.get('kernel', '?')}"
                            f"/{b.get('dtype', '?')}")
                print(f"[synthbench]   bucket {bucket}: {b['jobs']} jobs "
                      f"/ {b['batches']} batches, occupancy "
                      f"{b['occupancy_pct']:.1f}%{plan}", file=sys.stderr)

    if args.golden_out:
        with open(args.golden_out, "wb") as fh:
            for seq in polished:
                fh.write(b">" + seq.name.encode() + b"\n" + seq.data + b"\n")
        print(f"[synthbench] wrote golden {args.golden_out}", file=sys.stderr)

    # measured dispatch overhead: host_s = polish wall minus the
    # device-stage seconds (dispatch + result wait; clamped at 0 when
    # deep pipelining makes the stage sums exceed the wall) — the
    # number the fused single-launch program exists to shrink,
    # published in the artifact's `fused` block for perfgate
    fused_block = None
    if args.tpupoa_batches > 0:
        ss = polisher.stage_stats
        host_s = max(0.0, polish_s - ss["device_s"])
        fused_block = {
            "mode": (fused_mode_label
                     or os.environ.get("RACON_TPU_FUSED") or "auto"),
            "engine": args.engine or "session",
            "launches": ss["launches"],
            "chunks": ss["chunks"],
            "device_s": round(ss["device_s"], 3),
            "host_s": round(host_s, 3),
            "host_frac": round(host_s / polish_s, 4)
            if polish_s > 0 else 0.0,
        }
        print(f"[synthbench] dispatch: {fused_block['launches']} "
              f"launches / {fused_block['chunks']} chunks "
              f"(mode {fused_block['mode']}), host overhead "
              f"{fused_block['host_s']}s "
              f"({fused_block['host_frac'] * 100:.1f}% of polish wall)",
              file=sys.stderr)

    # throughput first: the identity metric below costs O(genome^2/64)
    # Myers time at multi-Mb scale, and the perf number must survive a
    # wall-cap hitting mid-metric
    print(f"[synthbench] init {init_s:.1f}s  polish {polish_s:.1f}s  "
          f"({n_windows} windows, {n_windows / polish_s:.1f} windows/s)",
          file=sys.stderr)
    d_draft = edit_distance(draft, truth)
    d_pol = edit_distance(polished[0].data, truth)
    print(f"[synthbench] draft error {d_draft / genome_len * 100:.2f}%  "
          f"polished error {d_pol / genome_len * 100:.2f}%  "
          f"(identity {100 - d_pol / genome_len * 100:.3f}%)",
          file=sys.stderr)
    if args.json:
        import json

        from racon_tpu.parallel.mesh import mesh_info

        artifact = {
            "mode": "synth",
            "synth": {
                "windows_per_s": round(n_windows / polish_s, 3)
                if polish_s > 0 else 0.0,
                "windows": n_windows,
                "init_s": round(init_s, 3),
                "polish_s": round(polish_s, 3),
                "identity_pct": round(100 - d_pol / genome_len * 100, 4),
                "genome_kb": args.genome_kb,
                "coverage": args.coverage,
                "seed": args.seed,
            },
            # per-bucket occupancy INCLUDING the dispatched kernel/dtype
            # choice — the autotuner's decision made visible per run
            "occupancy": polisher.occupancy_stats,
            # the mesh this number was measured on: perfgate refuses
            # cross-mesh comparisons (1-chip vs 8-chip windows/s is a
            # different machine, not a regression)
            "mesh": mesh_info(),
        }
        if fused_block is not None:
            # measured dispatch-loop numbers (host overhead fraction,
            # launch counts) — perfgate gates fused.host_frac whenever
            # this block is present
            artifact["fused"] = fused_block
        if dispatch_ab is not None:
            artifact["dispatch_overhead"] = dispatch_ab
        with open(args.json, "w") as fh:
            json.dump(artifact, fh, indent=1, sort_keys=True)
        print(f"[synthbench] wrote artifact {args.json}", file=sys.stderr)
    try:
        import resource

        rss_kb = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
        print(f"[synthbench] peak host RSS {rss_kb / 1024:.0f} MiB",
              file=sys.stderr)
    except Exception:
        pass
    if dispatch_ab is not None and not dispatch_ab["identical"]:
        return 1  # the fused program moved a byte: that is a bug
    return 0


if __name__ == "__main__":
    sys.exit(main())
