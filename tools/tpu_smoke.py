"""On-chip smoke + timing sequence (run when the TPU tunnel is up).

Runs, in order, each in its own guarded subprocess with wall-clock caps:
  1. device probe — jax init + one matmul, timed;
  2. session-engine precompile (4 bucket programs), timed;
  3. fused-engine precompile (sample-depth buckets), timed;
  4. an 8-window real-data polish per engine, timed, byte-checked
     against the host engine;
  5. the full bench (both engines + aligner smoke + host baseline).

Usage: python tools/tpu_smoke.py [--skip-bench]
Everything is logged to stderr; the bench JSON line goes to stdout.
The script exists so a transient tunnel window can be exploited with one
command — round-3's lesson is that on-chip time is scarce and the first
run must collect everything needed to diagnose performance.
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

PROBE = """
import time; t0=time.time()
import jax
ds = jax.devices()
import jax.numpy as jnp
x = jnp.ones((512,512)); (x@x).block_until_ready()
print(f"probe: devices={ds} init+matmul={time.time()-t0:.1f}s", flush=True)
"""

SESSION_PRE = """
import time
from racon_tpu.ops.poa_graph import DeviceGraphPOA
eng = DeviceGraphPOA(5, -4, -8)
t=time.time(); eng.precompile()
print(f"session precompile ({len(eng.buckets)} buckets, "
      f"batch_rows={eng.batch_rows}): {time.time()-t:.1f}s", flush=True)
"""

FUSED_PRE = """
import time
from racon_tpu.ops.poa_fused import FusedPOA
# banded_only=True matches what the bench's timed polish constructs
# (create_polisher's tpu_banded_alignment default) — the fused builder's
# programs are keyed on it, so a mismatch would waste this precompile
eng = FusedPOA(5, -4, -8, banded_only=True)
t=time.time(); eng.precompile(max_depth=40)
print(f"fused precompile (B={eng.B}): {time.time()-t:.1f}s", flush=True)
"""

PALLAS_PROFILE = """
# XLA-scan vs Pallas per bucket on synthetic jobs: the measurement that
# decides which DP program is the on-chip default (round-4 verdict #9).
import time
import numpy as np
import jax
from __graft_entry__ import _poa_example
from racon_tpu.ops.poa_graph import BUCKETS, RING, graph_aligner
from racon_tpu.ops.poa_pallas import fits_vmem, window_sweep

B = 32
for (nb, lb) in BUCKETS:
    args = _poa_example(nb, lb, B, seed=7)
    # the ring width production runs (_scan_kernel), so the XLA-vs-Pallas
    # decision times the shipped configuration (ADVICE round-5: a
    # hardcoded ring=64 went stale when RING was raised to 128)
    xla = graph_aligner(nb, lb, 4, 5, -4, -8,
                        ring=RING if nb > RING else 0)
    t = time.time(); r_x = np.asarray(xla(*args)); tx_c = time.time() - t
    t = time.time()
    for _ in range(3):
        r_x = np.asarray(xla(*args))
    tx = (time.time() - t) / 3
    line = f"bucket ({nb},{lb}) B={B}: xla {tx*1e3:.1f}ms (compile {tx_c:.1f}s)"
    if fits_vmem(nb, lb):
        interp = jax.default_backend() == "cpu"
        pal = window_sweep(nb, lb, 4, 5, -4, -8, interpret=interp)
        nn = np.full(B, nb, np.int32)
        t = time.time(); r_p = np.asarray(pal(*args, nn)); tp_c = time.time() - t
        t = time.time()
        for _ in range(3):
            r_p = np.asarray(pal(*args, nn))
        tp = (time.time() - t) / 3
        same = np.array_equal(r_x, r_p)
        line += (f"  pallas {tp*1e3:.1f}ms (compile {tp_c:.1f}s) "
                 f"identical={same} winner="
                 f"{'pallas' if tp < tx else 'xla'}")
    else:
        line += "  pallas: exceeds VMEM budget"
    print(line, flush=True)
"""

MINI = """
import time
from racon_tpu.core.polisher import create_polisher, PolisherType
from racon_tpu.native import poa_batch
D = "/root/reference/test/data/"
p = create_polisher(D+"sample_reads.fastq.gz", D+"sample_overlaps.paf.gz",
                    D+"sample_layout.fasta.gz", PolisherType.kC, 500, 10.0,
                    0.3, True, 5, -4, -8, num_threads=1)
p.initialize()
wins = [w for w in p.windows if len(w.sequences) >= 3][:8]
packed = [[(w.sequences[i], w.qualities[i], w.positions[i][0],
            w.positions[i][1]) for i in range(len(w.sequences))]
          for w in wins]
host = poa_batch(packed, 5, -4, -8)
import os
fused = os.environ.get("SMOKE_ENGINE") == "fused"
if fused:
    from racon_tpu.ops.poa_fused import FusedPOA
    # banded_only=True matches FUSED_PRE and the bench polish, so this
    # step reuses the precompiled programs instead of compiling cold
    eng = FusedPOA(5, -4, -8, num_threads=1, banded_only=True)
    t=time.time(); res, st = eng.consensus(packed, fallback=False)
else:
    from racon_tpu.ops.poa_graph import DeviceGraphPOA
    eng = DeviceGraphPOA(5, -4, -8, num_threads=1)
    t=time.time(); res, st = eng.consensus(packed)
dt=time.time()-t
ok = sum(int(r is not None and r[0] == h[0]) for r, h in zip(res, host))
on_dev = int((st == 0).sum())
print(f"mini polish ({os.environ.get('SMOKE_ENGINE','session')}): "
      f"{ok}/{len(wins)} byte-identical, {on_dev}/{len(wins)} device-built, "
      f"{dt:.1f}s incl. compile", flush=True)
# a smoke pass requires the DEVICE to have done the work — silent host
# fallback must fail the step, or a dead device path green-lights
if fused:
    # the fused engine's real-data contract allows rare topo-order tie
    # divergence (banded_only additionally skips the clip retry); the
    # session engine below stays byte-identical everywhere
    assert ok >= len(wins) - 1, "fused consensus diverged beyond contract"
else:
    assert ok == len(wins), "consensus diverged from host"
assert on_dev == len(wins), "windows fell back off the device"
"""


def step(name: str, code: str, cap: float, env_extra=None) -> bool:
    env = dict(os.environ, **(env_extra or {}))
    env.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/racon_tpu_jax_cache")
    t = time.time()
    try:
        proc = subprocess.run([sys.executable, "-c", code], timeout=cap,
                              cwd=REPO, env=env, capture_output=True,
                              text=True)
    except subprocess.TimeoutExpired as e:
        # the partial output is the diagnosis — never drop it
        for stream in (e.stdout, e.stderr):
            if stream:
                text = (stream.decode(errors="replace")
                        if isinstance(stream, bytes) else stream)
                sys.stderr.write(text[-3000:])
        print(f"[smoke] {name}: TIMEOUT after {cap:.0f}s", file=sys.stderr)
        return False
    sys.stderr.write(proc.stderr[-3000:])
    for line in proc.stdout.splitlines():
        print(f"[smoke] {name}: {line}", file=sys.stderr)
    print(f"[smoke] {name}: rc={proc.returncode} wall={time.time()-t:.1f}s",
          file=sys.stderr)
    return proc.returncode == 0


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--skip-bench", action="store_true")
    args = ap.parse_args()

    if not step("probe", PROBE, 420):
        print("[smoke] tunnel unreachable — aborting", file=sys.stderr)
        return 1
    ok = [
        step("session-precompile", SESSION_PRE, 600),
        step("fused-precompile", FUSED_PRE, 600),
        step("mini-session", MINI, 600),
        step("mini-fused", MINI, 600, {"SMOKE_ENGINE": "fused"}),
    ]
    # informational: the XLA-vs-Pallas per-bucket decision data (never
    # gates the smoke — its output picks the default DP path later)
    step("pallas-profile", PALLAS_PROFILE, 900)
    if not args.skip_bench:
        env = dict(os.environ)
        env.setdefault("RACON_TPU_POA_BATCHES", "1")
        proc = subprocess.run([sys.executable,
                               os.path.join(REPO, "bench.py")], cwd=REPO,
                              env=env)
        return proc.returncode or int(not all(ok))
    return int(not all(ok))


if __name__ == "__main__":
    sys.exit(main())
