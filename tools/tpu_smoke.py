"""On-chip smoke + timing sequence (run when the TPU tunnel is up).

Runs, in order, each in its own guarded subprocess with wall-clock caps:
  1. device probe — jax init + one matmul, timed;
  2. session-engine precompile (4 bucket programs), timed;
  3. fused-engine precompile (sample-depth buckets), timed;
  4. an 8-window real-data polish per engine, timed, byte-checked
     against the host engine;
  5. the full bench (both engines + aligner smoke + host baseline).

Usage: python tools/tpu_smoke.py [--skip-bench]
Everything is logged to stderr; the bench JSON line goes to stdout.
The script exists so a transient tunnel window can be exploited with one
command — round-3's lesson is that on-chip time is scarce and the first
run must collect everything needed to diagnose performance.
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

PROBE = """
import time; t0=time.time()
import jax
ds = jax.devices()
import jax.numpy as jnp
x = jnp.ones((512,512)); (x@x).block_until_ready()
print(f"probe: devices={ds} init+matmul={time.time()-t0:.1f}s", flush=True)
"""

SESSION_PRE = """
import time
from racon_tpu.ops.poa_graph import DeviceGraphPOA
eng = DeviceGraphPOA(5, -4, -8)
t=time.time(); eng.precompile()
print(f"session precompile ({len(eng.buckets)} buckets, "
      f"batch_rows={eng.batch_rows}): {time.time()-t:.1f}s", flush=True)
"""

FUSED_PRE = """
import time
from racon_tpu.ops.poa_fused import FusedPOA
# banded_only=True matches what the bench's timed polish constructs
# (create_polisher's tpu_banded_alignment default) — the fused builder's
# programs are keyed on it, so a mismatch would waste this precompile
eng = FusedPOA(5, -4, -8, banded_only=True)
t=time.time(); eng.precompile(max_depth=40)
print(f"fused precompile (B={eng.B}): {time.time()-t:.1f}s", flush=True)
"""

PALLAS_PROFILE = """
# XLA-vs-Pallas (and int32-vs-int16) per bucket on synthetic jobs: the
# measurement that decides which DP program is the on-chip default
# (round-4 verdict #9). Since PR 9 this runs through the persisted
# autotuner (racon_tpu/sched/autotune.py): winners land in a JSON table
# next to the XLA compile cache, which RACON_TPU_PALLAS=auto dispatches
# from — so this step profiles ONCE and every later run (warm serve
# jobs included) reuses the measured plan. Re-running with a warm table
# profiles nothing (fresh=no below).
from racon_tpu.ops.poa_graph import BUCKETS, MAX_PRED
from racon_tpu.sched.autotune import Autotuner

at = Autotuner()
# session buckets at the PRODUCTION dispatch key: DeviceGraphPOA._plan
# looks winners up by (match, mismatch, gap, max_pred) — the polisher/
# CLI default scoring (3, -5, -4) and the engine's MAX_PRED. Profiling
# any other params writes entries no warm run would ever consult (a
# custom-scoring deployment re-runs this step with its own params).
for (nb, lb) in BUCKETS:
    ent, fresh = at.profile_session_bucket(nb, lb, MAX_PRED, 3, -5, -4,
                                           rows=32)
    print(f"session ({nb},{lb}): winner {ent['kernel']}:{ent['dtype']} "
          f"identical={ent['identical']} fresh={'yes' if fresh else 'no'} "
          f"ms={ent['ms']}", flush=True)
# the aligner plane: every band the auto rule can dispatch per bucket.
# BatchAligner._band_for quantizes 10% of the bucket's MEAN pair length
# up to a multiple of 128, so bucket `edge` requests some band in
# 128..round128(edge * 0.1) — profile them all or the table misses the
# bucket the data actually lands on.
for edge in (512, 1024, 2048, 4096):
    top = max(128, (int(edge * 0.1) + 127) // 128 * 128)
    for band in range(128, top + 128, 128):
        ent, fresh = at.profile_aligner_bucket(edge, band)
        print(f"aligner ({edge},{band}): winner "
              f"{ent['kernel']}:{ent['dtype']} "
              f"identical={ent['identical']} "
              f"fresh={'yes' if fresh else 'no'} ms={ent['ms']}",
              flush=True)
# the fused-loop plane: split chained dispatch vs the single-launch
# fused align->window-slice->POA program, per depth bucket at the
# PRODUCTION consult key — FusedPOA._fused_plan looks winners up by
# (env_max_nodes(), MAX_LEN, leading chain bucket) with the CLI
# default scoring and the engine's MAX_PRED, so these entries are
# exactly what RACON_TPU_FUSED=auto dispatches from.
from racon_tpu.ops.poa_fused import DEPTH_BUCKETS
from racon_tpu.ops.poa_graph import MAX_LEN, env_max_nodes

N = env_max_nodes()
for d in DEPTH_BUCKETS:
    ent, fresh = at.profile_fused_bucket(N, MAX_LEN, d, MAX_PRED,
                                         3, -5, -4)
    print(f"fused_loop ({N},{MAX_LEN},{d}): winner "
          f"{ent['kernel']}:{ent['dtype']} "
          f"identical={ent['identical']} "
          f"fresh={'yes' if fresh else 'no'} ms={ent['ms']}",
          flush=True)
path = at.save()
print(f"winner table ({len(at.table)} entries) -> {path}", flush=True)
"""

MINI = """
import time
from racon_tpu.core.polisher import create_polisher, PolisherType
from racon_tpu.native import poa_batch
D = "/root/reference/test/data/"
p = create_polisher(D+"sample_reads.fastq.gz", D+"sample_overlaps.paf.gz",
                    D+"sample_layout.fasta.gz", PolisherType.kC, 500, 10.0,
                    0.3, True, 5, -4, -8, num_threads=1)
p.initialize()
wins = [w for w in p.windows if len(w.sequences) >= 3][:8]
packed = [[(w.sequences[i], w.qualities[i], w.positions[i][0],
            w.positions[i][1]) for i in range(len(w.sequences))]
          for w in wins]
host = poa_batch(packed, 5, -4, -8)
import os
fused = os.environ.get("SMOKE_ENGINE") == "fused"
if fused:
    from racon_tpu.ops.poa_fused import FusedPOA
    # banded_only=True matches FUSED_PRE and the bench polish, so this
    # step reuses the precompiled programs instead of compiling cold
    eng = FusedPOA(5, -4, -8, num_threads=1, banded_only=True)
    t=time.time(); res, st = eng.consensus(packed, fallback=False)
else:
    from racon_tpu.ops.poa_graph import DeviceGraphPOA
    eng = DeviceGraphPOA(5, -4, -8, num_threads=1)
    t=time.time(); res, st = eng.consensus(packed)
dt=time.time()-t
ok = sum(int(r is not None and r[0] == h[0]) for r, h in zip(res, host))
on_dev = int((st == 0).sum())
print(f"mini polish ({os.environ.get('SMOKE_ENGINE','session')}): "
      f"{ok}/{len(wins)} byte-identical, {on_dev}/{len(wins)} device-built, "
      f"{dt:.1f}s incl. compile", flush=True)
# a smoke pass requires the DEVICE to have done the work — silent host
# fallback must fail the step, or a dead device path green-lights
if fused:
    # the fused engine's real-data contract allows rare topo-order tie
    # divergence (banded_only additionally skips the clip retry); the
    # session engine below stays byte-identical everywhere
    assert ok >= len(wins) - 1, "fused consensus diverged beyond contract"
else:
    assert ok == len(wins), "consensus diverged from host"
assert on_dev == len(wins), "windows fell back off the device"
"""


def step(name: str, code: str, cap: float, env_extra=None) -> bool:
    env = dict(os.environ, **(env_extra or {}))
    env.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/racon_tpu_jax_cache")
    t = time.time()
    try:
        proc = subprocess.run([sys.executable, "-c", code], timeout=cap,
                              cwd=REPO, env=env, capture_output=True,
                              text=True)
    except subprocess.TimeoutExpired as e:
        # the partial output is the diagnosis — never drop it
        for stream in (e.stdout, e.stderr):
            if stream:
                text = (stream.decode(errors="replace")
                        if isinstance(stream, bytes) else stream)
                sys.stderr.write(text[-3000:])
        print(f"[smoke] {name}: TIMEOUT after {cap:.0f}s", file=sys.stderr)
        return False
    sys.stderr.write(proc.stderr[-3000:])
    for line in proc.stdout.splitlines():
        print(f"[smoke] {name}: {line}", file=sys.stderr)
    print(f"[smoke] {name}: rc={proc.returncode} wall={time.time()-t:.1f}s",
          file=sys.stderr)
    return proc.returncode == 0


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--skip-bench", action="store_true")
    args = ap.parse_args()

    if not step("probe", PROBE, 420):
        print("[smoke] tunnel unreachable — aborting", file=sys.stderr)
        return 1
    ok = [
        step("session-precompile", SESSION_PRE, 600),
        step("fused-precompile", FUSED_PRE, 600),
        step("mini-session", MINI, 600),
        step("mini-fused", MINI, 600, {"SMOKE_ENGINE": "fused"}),
    ]
    # informational: the XLA-vs-Pallas per-bucket decision data (never
    # gates the smoke — its output picks the default DP path later)
    step("pallas-profile", PALLAS_PROFILE, 900)
    if not args.skip_bench:
        env = dict(os.environ)
        env.setdefault("RACON_TPU_POA_BATCHES", "1")
        proc = subprocess.run([sys.executable,
                               os.path.join(REPO, "bench.py")], cwd=REPO,
                              env=env)
        return proc.returncode or int(not all(ok))
    return int(not all(ok))


if __name__ == "__main__":
    sys.exit(main())
