"""Critical-path and cost report over a merged distributed trace.

A routed `submit --trace-out` job produces ONE Chrome-trace JSON with
a process track per participant — client (pid 1), router (pid 2), one
track per replica (pid 3+), all on the client clock (serve/client.py
merge_trace; the replica tracks chain the router's per-replica clock
handshake onto the client's). This tool walks that shard DAG and
answers the question the artifact exists for: WHICH hop bounded the
job's wall clock, and where inside it did the time go:

    python tools/tracereport.py merged.json [--check] [--json]

The report finds the critical shard (the `router.shard` span that
finished last), then attributes the job wall — `router.plan` start to
`router.merge` end — into stages:

    plan      router-side target parse + shard planning
    requeue   time lost to the critical shard's FAILED attempts
              (replica loss -> requeue), first dispatch to the final
              attempt's dispatch
    hold      final-attempt replica acquisition while the PR-18
              autoscale idle-hold was engaged
    wait      final-attempt replica acquisition without the hold
              (busy-wait for a routable replica)
    queue     replica-side queue wait (serve.queue_wait, child trace)
    device    lane iteration device time (serve.iteration dur minus
              its measured host_s) for the critical child
    host      the iterations' measured host overhead (host_s)
    gather    replica-side serve.job wall not inside iterations —
              align/prep, incremental stitch, frame encoding
    net       child request wall not inside the replica job — frame
              transport + enqueue admission
    merge     router-side group assembly / stats aggregation / final
              frame build
    other     the wall's residual (shard-join gap, span rounding,
              clock-bracket skew between tracks)

plus a `wincache` estimate (time NOT spent, from the rounds cache
hits when the stats block carries them — informational, never part of
the partition). Direct (router-less) traces degrade to the same
report over queue/device/host/gather. Per-tenant device-seconds ride
along when the shard batches carry cost accounting (`tenant` /
`device_share_s`).

`--check` turns the report into a self-consistency gate (the CI /
faultcheck shape, rc 2 on any problem):

  - the stage partition sums to the job wall (exact by construction;
    each named stage must also be non-negative beyond the clock
    bracket - the chained min-RTT handshake bounds per-track skew)
  - span-sums-vs-stage_stats: per shard, the serve.iteration spans
    pulled from the replica's flight ring must sum to that shard's
    reported batch device_s (the same perf_counter endpoints feed
    both, so disagreement means dropped spans or a broken clock
    chain)
  - the `router.requeue` instants in the trace match the router
    block's requeue count, and every shard in `shards_detail` has its
    dispatch + shard spans present
  - the span-derived wall agrees with the router block's measured
    wall_s

Works from the file alone: everything it needs (spans + the stats
snapshot in `trace_context`) rides inside the artifact."""

from __future__ import annotations

import argparse
import json
import sys


def _spans(events, name):
    return [e for e in events
            if e.get("ph") == "X" and e.get("name") == name]


def _instants(events, name):
    return [e for e in events
            if e.get("ph") == "i" and e.get("name") == name]


def _dur_s(ev) -> float:
    return float(ev.get("dur", 0.0)) / 1e6


def _end(ev) -> float:
    return float(ev.get("ts", 0.0)) + float(ev.get("dur", 0.0))


def _arg(ev, key, default=None):
    return (ev.get("args") or {}).get(key, default)


def clock_bracket_s(ctx: dict) -> float:
    """Worst-case cross-track skew: each handshake is good to
    ±rtt/2, and a replica track chains two handshakes."""
    rtt = float(ctx.get("clock_rtt_s") or 0.0)
    worst = max((float(r.get("rtt_s") or 0.0)
                 for r in ctx.get("replicas") or []), default=0.0)
    return (rtt + worst) / 2.0


def load(path: str) -> dict:
    with open(path) as fh:
        doc = json.load(fh)
    if not isinstance(doc, dict) or "traceEvents" not in doc:
        raise ValueError(f"{path}: not a Chrome-trace document")
    return doc


def shard_lanes(events, trace_id: str) -> dict[int, dict]:
    """Per-shard view of the router's spans: dispatch attempts and
    shard (child request) spans in time order, plus the child trace id
    every replica-side span carries."""
    lanes: dict[int, dict] = {}
    for name in ("router.dispatch", "router.shard"):
        for ev in _spans(events, name):
            k = _arg(ev, "shard")
            if k is None:
                continue
            lane = lanes.setdefault(
                int(k), {"dispatch": [], "shard": [],
                         "tid": _arg(ev, "trace_id")})
            lane[name.split(".", 1)[1]].append(ev)
    for lane in lanes.values():
        lane["dispatch"].sort(key=lambda e: e.get("ts", 0.0))
        lane["shard"].sort(key=lambda e: e.get("ts", 0.0))
    return lanes


def child_spans(events, tid: str) -> dict:
    """Replica-side spans tagged with one child trace id."""

    def _tagged(name):
        out = []
        for ev in _spans(events, name):
            if _arg(ev, "trace_id") == tid:
                out.append(ev)
            else:
                tids = _arg(ev, "trace_ids") or []
                if isinstance(tids, (list, tuple)) and tid in tids:
                    out.append(ev)
        return out

    return {"queue_wait": _tagged("serve.queue_wait"),
            "job": _tagged("serve.job"),
            "iterations": _tagged("serve.iteration")}


def _iteration_buckets(iters) -> tuple[float, float]:
    """(device_s, host_s) split of the iteration spans: host is the
    measured per-iteration overhead each span carries."""
    device = host = 0.0
    for ev in iters:
        h = float(_arg(ev, "host_s", 0.0) or 0.0)
        d = _dur_s(ev)
        host += min(h, d)
        device += max(0.0, d - h)
    return device, host


def analyze(doc: dict) -> dict:
    """The report body: critical path + stage attribution + checks
    input. Raises ValueError when the document has no job spans."""
    events = doc.get("traceEvents") or []
    ctx = doc.get("trace_context") or {}
    stats = ctx.get("stats") or {}
    trace_id = ctx.get("trace_id") or ""
    plan = _spans(events, "router.plan")
    routed = bool(plan)
    out: dict = {"trace_id": trace_id,
                 "job_id": ctx.get("job_id"),
                 "routed": routed,
                 "bracket_s": clock_bracket_s(ctx)}

    if not routed:
        # direct submit: one replica track, no router hops
        jobs = _spans(events, "serve.job")
        if not jobs:
            raise ValueError("no router.plan or serve.job span - not "
                             "a merged job trace")
        job = jobs[0]
        qws = _spans(events, "serve.queue_wait")
        qw = _dur_s(qws[0]) if qws else 0.0
        iters = _spans(events, "serve.iteration")
        device, host = _iteration_buckets(iters)
        isum = sum(_dur_s(e) for e in iters)
        wall = (_end(job) - (qws[0].get("ts", job.get("ts", 0.0))
                             if qws else job.get("ts", 0.0))) / 1e6
        stages = {"queue": qw, "device": device, "host": host,
                  "gather": max(0.0, _dur_s(job) - isum)}
        stages["other"] = wall - sum(stages.values())
        out.update(wall_s=wall, stages=stages, shards={},
                   critical=None,
                   path=["queue", "device", "gather"])
        out["iteration_span_sums"] = {0: isum}
        return out

    plan = plan[0]
    merges = _spans(events, "router.merge")
    if not merges:
        raise ValueError("routed trace has no router.merge span "
                         "(failed job?)")
    merge = merges[-1]
    wall = (_end(merge) - float(plan.get("ts", 0.0))) / 1e6
    lanes = shard_lanes(events, trace_id)
    shards: dict[int, dict] = {}
    crit_k, crit_end = None, -1.0
    for k, lane in sorted(lanes.items()):
        tid = lane["tid"] or f"{trace_id}.s{k}"
        rep = child_spans(events, tid)
        final_shard = lane["shard"][-1] if lane["shard"] else None
        device, host = _iteration_buckets(rep["iterations"])
        isum = sum(_dur_s(e) for e in rep["iterations"])
        qw = sum(_dur_s(e) for e in rep["queue_wait"])
        jb = sum(_dur_s(e) for e in rep["job"])
        hold = sum(_dur_s(e) for e in lane["dispatch"]
                   if _arg(e, "held"))
        wait = sum(_dur_s(e) for e in lane["dispatch"]
                   if not _arg(e, "held"))
        requeue = 0.0
        if len(lane["dispatch"]) > 1:
            first = float(lane["dispatch"][0].get("ts", 0.0))
            last = lane["dispatch"][-1]
            requeue = (float(last.get("ts", 0.0)) - first) / 1e6
            # the final attempt's own acquisition is hold/wait, not
            # requeue penalty
            hold = _dur_s(last) if _arg(last, "held") else 0.0
            wait = 0.0 if _arg(last, "held") else _dur_s(last)
        info = {"trace_id": tid,
                "replica": (_arg(final_shard, "replica")
                            if final_shard else None),
                "attempts": len(lane["dispatch"]),
                "requeue_s": requeue, "hold_s": hold, "wait_s": wait,
                "queue_s": qw, "device_s": device, "host_s": host,
                "gather_s": max(0.0, jb - isum),
                "net_s": max(0.0, (_dur_s(final_shard)
                                   if final_shard else 0.0) - qw - jb),
                "iteration_span_sum_s": isum,
                "end_us": _end(final_shard) if final_shard else 0.0}
        shards[k] = info
        if final_shard is not None and info["end_us"] > crit_end:
            crit_k, crit_end = k, info["end_us"]
    if crit_k is None:
        raise ValueError("routed trace has no router.shard spans")
    c = shards[crit_k]
    stages = {"plan": _dur_s(plan),
              "requeue": c["requeue_s"], "hold": c["hold_s"],
              "wait": c["wait_s"], "queue": c["queue_s"],
              "device": c["device_s"], "host": c["host_s"],
              "gather": c["gather_s"], "net": c["net_s"],
              "merge": _dur_s(merge)}
    stages["other"] = wall - sum(stages.values())
    out.update(wall_s=wall, stages=stages, shards=shards,
               critical=crit_k,
               path=["plan", f"shard {crit_k}"
                     + (f" @{c['replica']}" if c["replica"] else ""),
                     "merge"])
    out["requeue_instants"] = len(_instants(events, "router.requeue"))
    out["stream_instants"] = len(_instants(events, "router.stream"))
    # per-tenant cost, when the shard batches carry the accounting
    tenants: dict[str, float] = {}
    for d in (stats.get("router") or {}).get("shards_detail") or []:
        batch = d.get("batch") or {}
        if "device_share_s" in batch:
            t = batch.get("tenant") or "<anon>"
            tenants[t] = tenants.get(t, 0.0) + batch["device_share_s"]
    if tenants:
        out["tenant_device_s"] = tenants
    return out


def check(doc: dict, rep: dict) -> list[str]:
    """Self-consistency problems (empty = green)."""
    problems: list[str] = []
    ctx = doc.get("trace_context") or {}
    stats = ctx.get("stats") or {}
    eps = 2.0 * rep["bracket_s"] + 1e-3
    drift = abs(rep["wall_s"] - sum(rep["stages"].values()))
    if drift > 1e-6:
        problems.append(
            f"stage partition does not sum to wall: drift {drift:.6f}s")
    for name, v in rep["stages"].items():
        if v < -eps:
            problems.append(
                f"stage {name} is negative beyond the clock bracket "
                f"({v:.4f}s < -{eps:.4f}s)")
    router = stats.get("router") or {}
    detail = router.get("shards_detail")
    if rep["routed"] and detail is not None:
        for d in detail:
            k = d.get("shard")
            batch = d.get("batch") or {}
            dev = batch.get("device_s")
            shard = rep["shards"].get(k)
            if shard is None:
                problems.append(f"shard {k} in shards_detail has no "
                                "dispatch/shard spans in the trace")
                continue
            if dev is not None and batch.get("iterations"):
                isum = shard["iteration_span_sum_s"]
                tol = max(0.05 * float(dev), 2e-3)
                if abs(isum - float(dev)) > tol:
                    problems.append(
                        f"shard {k}: iteration span sum {isum:.4f}s "
                        f"!= batch device_s {dev:.4f}s (tol "
                        f"{tol:.4f}s)")
    if rep["routed"] and router:
        want = router.get("requeues")
        got = rep.get("requeue_instants", 0)
        if want is not None and got != want:
            problems.append(
                f"router.requeue instants ({got}) != router block "
                f"requeues ({want})")
        wall_stat = router.get("wall_s")
        if wall_stat is not None:
            tol = max(0.10 * float(wall_stat), 0.05)
            if abs(rep["wall_s"] - float(wall_stat)) > tol:
                problems.append(
                    f"span wall {rep['wall_s']:.4f}s disagrees with "
                    f"router wall_s {wall_stat:.4f}s (tol {tol:.4f}s)")
    return problems


def wincache_estimate(ctx_stats: dict, rep: dict) -> float | None:
    """Rounds-cache time-saved estimate: hits x the measured
    per-dispatched-window device cost. None when no cache stats."""
    cache = (ctx_stats.get("rounds") or {}).get("cache")
    if not cache:
        return None
    hits = int(cache.get("hits", 0))
    misses = int(cache.get("misses", 0))
    device = rep["stages"].get("device", 0.0)
    if misses <= 0 or device <= 0:
        return 0.0
    return hits * (device / misses)


def render(rep: dict, saved: float | None) -> str:
    lines = []
    kind = "routed" if rep["routed"] else "direct"
    lines.append(
        f"tracereport: job {rep.get('job_id')} "
        f"(trace {rep.get('trace_id') or '-'}), {kind}, "
        f"{len(rep['shards']) or 1} shard(s), "
        f"wall {rep['wall_s']:.4f}s, "
        f"clock bracket +/-{rep['bracket_s'] * 1e3:.3f}ms")
    lines.append("critical path: " + " -> ".join(rep["path"]))
    lines.append(f"  {'stage':<10} {'seconds':>9} {'% wall':>7}")
    wall = rep["wall_s"] or 1.0
    for name, v in rep["stages"].items():
        lines.append(f"  {name:<10} {v:>9.4f} {100.0 * v / wall:>6.1f}%")
    lines.append(f"  {'sum':<10} {sum(rep['stages'].values()):>9.4f} "
                 f"{100.0:>6.1f}%")
    if saved is not None:
        lines.append(f"  wincache saved ~{saved:.4f}s "
                     "(est., not part of the wall)")
    if len(rep["shards"]) > 1:
        lines.append("shards:")
        for k, s in sorted(rep["shards"].items()):
            mark = " *" if k == rep["critical"] else ""
            lines.append(
                f"  s{k}{mark} @{s['replica']}: "
                f"attempts {s['attempts']}, queue {s['queue_s']:.4f}s, "
                f"device {s['device_s']:.4f}s, host {s['host_s']:.4f}s, "
                f"gather {s['gather_s']:.4f}s")
    for t, v in sorted((rep.get("tenant_device_s") or {}).items()):
        lines.append(f"tenant {t}: {v:.4f} device-seconds")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="tracereport",
        description="critical-path + cost attribution over a merged "
                    "distributed trace (submit --trace-out)")
    ap.add_argument("trace", help="merged Chrome-trace JSON")
    ap.add_argument("--check", action="store_true",
                    help="run the self-consistency checks; any "
                         "problem exits 2")
    ap.add_argument("--json", action="store_true",
                    help="emit the report as JSON instead of text")
    args = ap.parse_args(argv)
    try:
        doc = load(args.trace)
        rep = analyze(doc)
    except (OSError, ValueError, json.JSONDecodeError) as exc:
        print(f"tracereport: error: {exc}", file=sys.stderr)
        return 1
    ctx_stats = (doc.get("trace_context") or {}).get("stats") or {}
    saved = wincache_estimate(ctx_stats, rep)
    problems = check(doc, rep) if args.check else []
    if args.json:
        body = dict(rep)
        if saved is not None:
            body["wincache_saved_est_s"] = saved
        if args.check:
            body["problems"] = problems
        print(json.dumps(body, indent=2, sort_keys=True))
    else:
        print(render(rep, saved))
    if args.check:
        for p in problems:
            print(f"CHECK: {p}", file=sys.stderr)
        print(f"tracereport --check: "
              f"{'FAIL (' + str(len(problems)) + ' problem(s))' if problems else 'ok'}",
              file=sys.stderr)
        if problems:
            return 2
    return 0


if __name__ == "__main__":
    sys.exit(main())
